"""Control-plane replication: WAL-shipping warm standby + failover.

The reference operator survives process death because etcd is replicated
and the apiserver is stateless; this framework's `--role host` process is
both collapsed into one, so after PR 5 closed the node failure domain the
host itself was the last unprotected one. This module is the etcd/raft-lite
answer (PAPERS.md: etcd's WAL + snapshot replication), scoped to one warm
standby:

  primary   a normal `--role host --state-dir` process. Its `HostStore`
            keeps an in-memory ring of every journaled record tagged with a
            monotonic replication seq (`wal_page`), served at `GET /wal`;
            `GET /replication/snapshot` serves an atomic (state, watch-seq,
            WAL-cursor, resume-floor) capture for bootstrap; a host Lease
            (`HOST_LEASE_NAME`, renewed on the host's own store and
            therefore REPLICATED) is the failure detector.
  standby   `--standby-of <primary>` (`StandbyController` here): bootstraps
            from the snapshot, tails `/wal` with long-polls, applies each
            record through `APIServer.apply_replicated` — live watch
            notify, local write-ahead journal, primary resourceVersions
            and watch seqs preserved — and serves bounded-staleness reads
            while answering every write 503 NotLeader.

Seq lockstep is the point: `apply_replicated` advances the standby's watch
event counter exactly as the primary's own `_notify` did, so the standby's
resume ring assigns IDENTICAL seq numbers to identical events. Combined
with the accepted-epoch chain (`_ResumeRing.seed`), a surviving client that
presents its dead-primary watermarks to the promoted standby gets a DELTA
replay instead of a relist storm — failover costs survivors O(missed
events), which is what the PR 3 resume protocol was built to buy.

Promotion (lease expiry while disconnected, or the explicit `promote`
verb / `POST /promote`) drains the WAL tail already fetched, advances the
uid floor, flips the write gate, takes over the host lease with the
LeaderElector takeover arm (controllers/leader.py semantics), and runs the
`on_promote` callbacks the owning process registered (cluster services,
fleet plane). Clients fail over via `RemoteAPIServer(addresses=[primary,
standby])`: transport failures and NotLeader answers rotate the address,
watches heal by chained resume, and the write coalescer replays its
unacknowledged envelope as per-op conflicts.

Split-brain note: auto-promotion requires BOTH the replicated lease to be
expired AND the WAL tail to be disconnected for a full lease duration — a
partition where the primary still serves clients but not the standby can
still promote wrongly (the classic two-node limit; the reference leans on
etcd quorum for this). Clocks must be comparable across hosts (NTP); the
lease math is wall-clock.
"""

from __future__ import annotations

import logging
import threading
import time as _time
import uuid
from typing import Any, Callable, Dict, List, Optional

from training_operator_tpu.cluster.apiserver import encode_snapshot
from training_operator_tpu.cluster.store import HostStore, decode_snapshot
from training_operator_tpu.cluster.wire_transport import (
    ApiServerError,
    ApiUnavailableError,
    RemoteAPIServer,
)
from training_operator_tpu.utils import metrics

log = logging.getLogger(__name__)

# The host-primacy lease: who is allowed to accept writes. Renewed by the
# primary against its OWN store, so renewals journal -> ship -> apply, and
# the standby's local copy goes stale exactly when replication does.
HOST_LEASE_NAME = "training-host-primary"
HOST_LEASE_NAMESPACE = "operator-system"


def make_snapshot_source(api, store: HostStore, ring) -> Callable[[], Dict[str, Any]]:
    """The host side of `GET /replication/snapshot`: one atomic capture of
    (state refs, watch-event seq, WAL cursor+epoch, resume floors, epoch
    chain) under the API lock — mutators hold that lock when the journal
    sink assigns WAL seqs, so the cursor is exactly consistent with the
    captured state — with the expensive wire-encode done OUTSIDE it."""

    def snapshot_source() -> Dict[str, Any]:
        with api.locked():
            refs = api.snapshot_refs()
            seq = api.event_seq()
            wal_head, wal_epoch = store.wal_state()
            ring.sync()  # events committed before this instant are in-ring
            kind_seqs = ring.kind_seqs()
            epochs = sorted(ring.epochs)
        metrics.replication_snapshots_served.inc()
        return {
            "snap": encode_snapshot(refs),
            "seq": seq,
            "wal": wal_head,
            "wal_epoch": wal_epoch,
            "kind_seqs": kind_seqs,
            "ring_epochs": epochs,
        }

    return snapshot_source


def start_host_lease(cluster, identity: str, duration: float,
                     renew_interval: Optional[float] = None):
    """Run the host-primacy lease on the cluster clock: acquire/renew every
    duration/3 (controllers/leader.py semantics, reused verbatim). Returns
    the elector; the caller owns shutdown via elector.release()."""
    from training_operator_tpu.controllers.leader import LeaderElector

    elector = LeaderElector(
        cluster.api, cluster.clock.now, identity,
        lease_name=HOST_LEASE_NAME, namespace=HOST_LEASE_NAMESPACE,
        lease_duration=duration, renew_interval=renew_interval,
    )

    def tick():
        elector.tick()
        cluster.schedule_after(elector.renew_interval, tick)

    cluster.schedule_after(0.0, tick)
    return elector


class StandbyController:
    """The warm-standby role: bootstrap, tail, serve stale, promote.

    Owns the replication client against the primary and the standby's
    replication state machine. The owning process (``__main__.run_standby``
    or an in-process test stack) drives two things: the cluster step loop
    (timers: the lease monitor), and `maybe_complete_promotion()` once per
    iteration — promotion is REQUESTED from any thread (lease timer, the
    HTTP `/promote` handler) but COMPLETED only on the owner's loop, so
    service construction never races the step loop it will join.
    """

    def __init__(
        self,
        cluster,
        primary_url: str,
        store: Optional[HostStore] = None,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        poll_timeout: float = 2.0,
        lease_duration: float = 5.0,
        auto_promote: bool = True,
        identity: Optional[str] = None,
        page_limit: int = 1024,
    ):
        self.cluster = cluster
        self.api = cluster.api
        # Seq-lockstep tailing assumes ONE WAL stream; a sharded plane runs
        # one standby PROCESS per write shard (each a vanilla pair against
        # that shard's host), never one standby over a StoreShardSet —
        # reject the topology here rather than corrupt cursors downstream.
        if store is not None and not hasattr(store, "wal_page"):
            raise TypeError(
                "StandbyController requires a single-shard HostStore; run "
                "one standby per write shard (see cluster/shards.py)"
            )
        self.store = store
        self.primary_url = primary_url
        # Dedicated single-address client: resume/pipelining are watch/write
        # machinery this tail never uses, and rotation has nowhere to go.
        self.remote = RemoteAPIServer(
            primary_url, token=token, ca_file=ca_file,
            timeout=max(30.0, poll_timeout * 3), resume=False, pipeline=False,
        )
        self.poll_timeout = poll_timeout
        self.lease_duration = lease_duration
        self.auto_promote = auto_promote
        self.identity = identity or f"standby-{uuid.uuid4().hex[:8]}"
        self.page_limit = max(1, int(page_limit))
        # Set after the server exists (attach_server): the ring the
        # bootstrap seeds, and the promote hook's home.
        self.server = None
        self.elector = None  # set at promotion (host-lease takeover)
        self.on_promote: List[Callable[[], None]] = []
        # Replication cursor state (tailer thread only, once started).
        self._cursor = 0
        self._wal_epoch: Optional[str] = None
        self._chain_seed: Optional[Dict[str, Any]] = None  # pre-server seed
        # Lag as of the last page: (records behind, seconds behind).
        self.lag_records = 0
        self.lag_seconds = 0.0
        self.applied = 0
        self.bootstraps = 0
        self.apply_errors = 0
        self.auth_failed = False
        self.connected = False
        self._last_contact: Optional[float] = None  # monotonic
        self._last_apply: Optional[float] = None  # monotonic, successful apply
        self.promoted = False
        self._promote_reason: Optional[str] = None
        self._promote_requested = threading.Event()
        self._promote_done = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- bootstrap ---------------------------------------------------------

    def bootstrap(self) -> None:
        """Full-state sync from the primary: first contact installs the
        snapshot wholesale (store adopt + APIServer.restore); a RE-bootstrap
        (WAL ring outrun, or a new primary incarnation) diff-applies it
        through `apply_replicated` so live standby watchers see the changes
        as ordinary events. Either way the watch-event counter is pinned to
        the primary's (`set_event_seq`) and the resume ring inherits the
        shipped floors + epoch chain — the seq-lockstep foundation."""
        payload = self.remote.get_replication_snapshot()
        first = self.bootstraps == 0
        snap = payload["snap"]
        seed = (dict(payload.get("kind_seqs", {})),
                list(payload.get("ring_epochs", [])))
        if self.store is not None:
            self.store.adopt_snapshot(snap)
            self.store.attach(self.api)
        if first:
            objects, rv, events, pod_logs = decode_snapshot(snap)
            self.api.restore(objects, rv, events, pod_logs)
        else:
            # Raise the resume floors BEFORE the diff notifies: the diff's
            # events carry LOW standby-local seqs (the counter froze at the
            # outrun cursor), so a chained resume answered between diff and
            # seed would pass the too-old check against the stale floor yet
            # replay none of the gap — silently incomplete forever. Floor
            # first (max-merge, idempotent), and that client answers
            # too_old -> one honest relist instead.
            if self.server is not None:
                self.server.resume_ring.seed(*seed)
            self._diff_apply(snap)
        self.api.set_event_seq(int(payload.get("seq", 0)))
        self._cursor = int(payload.get("wal", 0))
        self._wal_epoch = payload.get("wal_epoch")
        if self.server is not None:
            self.server.resume_ring.seed(*seed)
        else:
            # Server not built yet (boot order: bootstrap -> serve); the
            # owner seeds at attach_server.
            self._chain_seed = seed
        self.bootstraps += 1
        metrics.replication_bootstraps.inc()
        log.info(
            "standby bootstrap #%d from %s: rv=%s seq=%s wal=%s",
            self.bootstraps, self.primary_url, snap.get("rv"),
            payload.get("seq"), payload.get("wal"),
        )

    def _diff_apply(self, snap: Dict[str, Any]) -> None:
        """Converge the live store onto a re-fetched snapshot using the
        replicated-record vocabulary: upsert every object whose stored
        resourceVersion differs, delete everything the snapshot no longer
        holds. Each change notifies exactly once, and there are at most as
        many diffs as records missed, so the diff seqs can never run past
        the primary's counter before set_event_seq re-pins it. Events and
        pod logs missed across an outrun gap stay missed (append-only
        diagnostics; the objects are the state that matters)."""
        from training_operator_tpu.cluster import wire

        rv = int(snap.get("rv", 0))
        keep = set()
        for data in snap.get("objects", []):
            obj = wire.decode(data)
            ns = getattr(obj.metadata, "namespace", "") or ""
            key = (obj.KIND, ns, obj.metadata.name)
            keep.add(key)
            if (self.api.resource_version(*key)
                    != obj.metadata.resource_version):
                self.api.apply_replicated({"op": "put", "obj": data})
        stale = []
        with self.api.locked():
            # Public enumeration (no _objects poke): the tailer thread is
            # the only writer on a read-only standby, but the lock keeps
            # the two-call walk one consistent cut regardless.
            for kind in self.api.object_counts():
                for ref in self.api.list_refs(kind):
                    key = (
                        kind,
                        getattr(ref.metadata, "namespace", "") or "",
                        ref.metadata.name,
                    )
                    if key not in keep:
                        stale.append(key)
        for kind, ns, name in stale:
            self.api.apply_replicated(
                {"op": "del", "kind": kind, "ns": ns, "name": name, "rv": rv}
            )

    # -- tailing -----------------------------------------------------------

    def start(self) -> None:
        """Start the WAL tailer thread and (with auto_promote) the lease
        monitor on the cluster clock. Call after bootstrap()."""
        self._thread = threading.Thread(
            target=self._tail_loop, name="wal-tail", daemon=True
        )
        self._thread.start()
        self.cluster.schedule_after(
            max(0.5, self.lease_duration / 3.0), self._lease_check
        )

    def stop(self) -> None:
        self._stop.set()

    def _fetch_page(self, timeout: float) -> Dict[str, Any]:
        return self.remote.get_wal(
            after=self._cursor, limit=self.page_limit, timeout=timeout,
        )

    def _apply_page(self, page: Dict[str, Any]) -> int:
        applied = 0
        last_t = None
        for rec in page.get("records", []):
            self.api.apply_replicated(rec["r"])
            self._cursor = int(rec["s"])
            last_t = rec.get("t")
            applied += 1
        self.applied += applied
        if applied:
            metrics.replication_records_applied.inc(amount=applied)
        head = int(page.get("head", self._cursor))
        self.lag_records = max(0, head - self._cursor)
        if self.lag_records == 0:
            self.lag_seconds = 0.0
        elif last_t is not None:
            # Behind mid-page: age the backlog from the newest record we DID
            # apply against the primary's own clock (no cross-host skew).
            self.lag_seconds = max(0.0, float(page.get("now", 0.0)) - float(last_t))
        metrics.replication_lag_records.set(value=float(self.lag_records))
        metrics.replication_lag_seconds.set(value=self.lag_seconds)
        self._last_apply = _time.monotonic()
        return applied

    def _tail_loop(self) -> None:
        while not self._stop.is_set() and not self.promoted:
            try:
                page = self._fetch_page(self.poll_timeout)
            except Exception as e:  # noqa: BLE001 — the tail outlives any fault
                if self._stop.is_set() or self.promoted:
                    return
                if isinstance(e, PermissionError):
                    # Config error (rotated bearer token, TLS pin mismatch):
                    # keep retrying — the operator may fix credentials —
                    # but LOUDLY (once per incident), and never let it read
                    # as a dead primary: auth-blind is not proof of death,
                    # and _lease_check auto-promoting here would split-brain
                    # against a healthy, still-serving primary.
                    if not self.auth_failed:
                        log.warning(
                            "wal tail: auth failure against %s: %s",
                            self.primary_url, e,
                        )
                    self.auth_failed = True
                else:
                    self.auth_failed = False
                    log.debug("wal tail: primary unreachable (%s)", e)
                self.connected = False
                # Lag grows while blind: age since the last applied record.
                if self._last_contact is not None:
                    self.lag_seconds = _time.monotonic() - self._last_contact
                    metrics.replication_lag_seconds.set(value=self.lag_seconds)
                self._stop.wait(min(0.5, self.poll_timeout))
                continue
            self._last_contact = _time.monotonic()
            self.connected = True
            self.auth_failed = False
            if self._stop.is_set() or self.promoted:
                # Promotion (or shutdown) raced this fetch: do NOT apply —
                # the promotion drain re-fetches from the same cursor, and
                # applying here too would double-apply the page (an extra
                # notify per record breaks the seq lockstep chained resume
                # depends on).
                return
            if page.get("reset") or page.get("wal_epoch") != self._wal_epoch:
                # Outrun (cursor below the primary's ring floor) or a NEW
                # primary incarnation: the tail can't be resumed — full
                # snapshot re-bootstrap, diff-applied into the live store.
                log.warning(
                    "wal tail reset (epoch %s -> %s): re-bootstrapping",
                    self._wal_epoch, page.get("wal_epoch"),
                )
                try:
                    self.bootstrap()
                except (ApiUnavailableError, ApiServerError) as e:
                    log.warning("re-bootstrap failed (%s); retrying", e)
                    self._stop.wait(min(0.5, self.poll_timeout))
                continue
            try:
                self._apply_page(page)
            except Exception as e:  # noqa: BLE001 — a sick standby must stay visible
                if self._stop.is_set() or self.promoted:
                    return
                # The fetch succeeded but the LOCAL apply did not (own
                # journal write failed, undecodable record). The cursor
                # stopped at the last record that did apply, so the next
                # fetch retries the remainder — but if the fault is
                # persistent the thread must not die with connected=True
                # and the lag gauges frozen at a healthy 0: that would
                # blind INV008 AND the auto-promotion disconnect check at
                # once. Surface the backlog as lag so the auditor fires.
                self.apply_errors += 1
                head = int(page.get("head", self._cursor))
                self.lag_records = max(0, head - self._cursor)
                # Age from the last record that DID apply — NOT _last_contact,
                # which every successful fetch resets to "now".
                since = self._last_apply or self._last_contact
                if since is not None:
                    self.lag_seconds = max(
                        self.lag_seconds, _time.monotonic() - since
                    )
                metrics.replication_lag_records.set(value=float(self.lag_records))
                metrics.replication_lag_seconds.set(value=self.lag_seconds)
                log.error("wal apply failed at seq %d: %s", self._cursor, e)
                self._stop.wait(min(0.5, self.poll_timeout))

    def lag(self) -> Dict[str, Any]:
        """The fleet/INV008 feed: current replication lag + role."""
        seconds = self.lag_seconds
        if not self.connected and self._last_contact is not None:
            seconds = max(seconds, _time.monotonic() - self._last_contact)
        return {
            "role": "primary" if self.promoted else "standby",
            "records": self.lag_records,
            "seconds": seconds,
            "connected": self.connected,
            "auth_failed": self.auth_failed,
            "applied": self.applied,
            "apply_errors": self.apply_errors,
            "bootstraps": self.bootstraps,
        }

    # -- promotion ---------------------------------------------------------

    def attach_server(self, server) -> None:
        """Wire the standby's own ApiHTTPServer: write gate, promote verb,
        and the inherited resume chain (seed deferred from bootstrap)."""
        self.server = server
        server.read_only_fn = lambda: not self.promoted
        server.promote_hook = self._promote_hook
        if self._chain_seed is not None:
            server.resume_ring.seed(*self._chain_seed)
            self._chain_seed = None

    def _promote_hook(self) -> Dict[str, Any]:
        """POST /promote (handler thread): request and wait for the owner's
        loop to complete the promotion — synchronous for the caller."""
        self.request_promotion("explicit promote verb")
        if not self._promote_done.wait(30.0):
            raise ApiServerError("promotion did not complete within 30s")
        return {
            "promoted": True,
            "identity": self.identity,
            "applied": self.applied,
            "seq": self.api.event_seq(),
        }

    def request_promotion(self, reason: str) -> None:
        if not self._promote_requested.is_set():
            self._promote_reason = reason
            self._promote_requested.set()

    def _lease_check(self) -> None:
        """The failure detector (cluster timer): promote only when the
        REPLICATED host lease is expired AND the WAL tail has been
        disconnected a full lease duration — while pages still flow, a
        stale lease just means replication lag, not a dead primary."""
        if self._stop.is_set() or self.promoted:
            return
        if self.auto_promote and not self._promote_requested.is_set():
            lease = self.api.try_get(
                "Lease", HOST_LEASE_NAMESPACE, HOST_LEASE_NAME
            )
            # auth_failed excluded: a standby that cannot AUTHENTICATE has
            # no evidence the primary is dead — only that its own
            # credentials are wrong. Explicit `promote` stays available.
            disconnected = not self.connected and not self.auth_failed and (
                self._last_contact is None
                or _time.monotonic() - self._last_contact >= self.lease_duration
            )
            if (lease is not None and disconnected
                    and lease.expired(self.cluster.clock.now())):
                log.warning(
                    "host lease held by %r expired and primary unreachable: "
                    "requesting promotion", lease.holder,
                )
                self.request_promotion("host lease expired")
        self.cluster.schedule_after(
            max(0.5, self.lease_duration / 3.0), self._lease_check
        )

    def maybe_complete_promotion(self) -> bool:
        """Owner-loop hook: complete a requested promotion. Returns True
        the first time the standby becomes the primary."""
        if self.promoted or not self._promote_requested.is_set():
            return False
        self._complete_promotion()
        return True

    def _complete_promotion(self) -> None:
        log.warning("promoting standby %s (%s)", self.identity,
                    self._promote_reason)
        # Stop the tailer FIRST and wait it out: the drain below re-fetches
        # from the shared cursor, and a tailer mid-long-poll applying the
        # same page concurrently would double-apply it (see _tail_loop's
        # post-fetch stop check, the other half of this handshake).
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=max(5.0, self.poll_timeout * 3))
        # Drain whatever WAL tail is still reachable — on a planned
        # promotion (explicit verb, primary alive) this closes the gap
        # before the write gate opens; on a crash it returns immediately
        # unreachable. Bounded by WALL CLOCK, not page count: a standby
        # thousands of records behind must not promote with acknowledged
        # writes still sitting on the reachable old primary. A reset page
        # (cursor outran the ring) can't be drained record-by-record — one
        # snapshot re-bootstrap diff-applies the gap instead.
        deadline = _time.monotonic() + max(5.0, self.poll_timeout * 3)
        rebootstrapped = False
        while _time.monotonic() < deadline:
            try:
                page = self._fetch_page(0.0)
            except (ApiUnavailableError, ApiServerError, PermissionError):
                break
            try:
                if page.get("reset") or page.get("wal_epoch") != self._wal_epoch:
                    if rebootstrapped:
                        break
                    rebootstrapped = True
                    self.bootstrap()
                    continue
                if self._apply_page(page) == 0:
                    break
            except (ApiUnavailableError, ApiServerError, PermissionError):
                break
            except Exception:  # noqa: BLE001 — promote anyway, but loudly
                log.exception("promotion drain: local apply failed at seq %d",
                              self._cursor)
                break
        if self.lag_records:
            log.warning(
                "promoting %d WAL records behind the last reachable head "
                "(seq %d)", self.lag_records, self._cursor,
            )
        # Replicated objects carry the PRIMARY's uids; the first local
        # create must not mint a colliding one.
        self.api.advance_uid_floor()
        self.promoted = True  # write gate opens (read_only_fn)
        self.lag_records = 0
        self.lag_seconds = 0.0
        metrics.replication_lag_records.set(value=0.0)
        metrics.replication_lag_seconds.set(value=0.0)
        metrics.replication_promotions.inc()
        # Take over the host-primacy lease NOW, expired or not: on a
        # planned promotion (explicit verb) the old primary is still
        # renewing, and waiting out its lease would leave the failover
        # record (holder + transitions) pointing at a host that no longer
        # owns the writes this store is already accepting. Force-write,
        # then keep renewing with the LeaderElector so a future standby of
        # THIS host has its failure detector.
        now = self.cluster.clock.now()
        lease = self.api.try_get("Lease", HOST_LEASE_NAMESPACE, HOST_LEASE_NAME)
        if lease is not None and lease.holder != self.identity:
            lease.holder = self.identity
            lease.acquire_time = now
            lease.renew_time = now
            lease.lease_duration = self.lease_duration
            lease.transitions += 1
            self.api.update(lease, check_version=False)
        self.elector = start_host_lease(
            self.cluster, self.identity, self.lease_duration
        )
        self.elector.tick()
        for cb in self.on_promote:
            try:
                cb()
            except Exception:
                log.exception("on_promote callback failed")
        self._promote_done.set()
        log.warning(
            "standby %s is now PRIMARY (seq=%d, %d records applied, "
            "%d bootstraps)",
            self.identity, self.api.event_seq(), self.applied, self.bootstraps,
        )
