"""Operator-side run loop for a remote API server: host-slaved clock,
tickers, timers, and the crash-proof main loop.

One of the four modules carved out of the original `cluster/httpapi.py`:
this one owns `SyncedClock` (lease/TTL arithmetic on HOST time) and
`RemoteRuntime` (the `Cluster`-shaped loop the operator stack and SDK run
against when the API server lives in another process). The transport lives
in `wire_transport.py`; the watch fanout in `wire_watch.py`; the server in
`wire_server.py`. `cluster/httpapi.py` remains the public facade
re-exporting all of it.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import threading
import time as _time
from typing import Any, Callable, List, Optional, Tuple

from training_operator_tpu.cluster.runtime import Clock
from training_operator_tpu.utils.locks import TrackedLock
from training_operator_tpu.cluster.wire_transport import (
    ApiServerError,
    ApiUnavailableError,
    RemoteAPIServer,
)

log = logging.getLogger(__name__)


class SyncedClock(Clock):
    """A clock slaved to the serving host's cluster clock via GET /time.

    Every timestamp a remote operator writes into shared state — lease
    acquire/renew times above all — must be comparable with timestamps other
    processes write. Per-process `time.monotonic()` epochs are machine-boot-
    relative: two operators on different machines would compare leases
    across incomparable epochs, permanently blocking takeover or causing
    instant split-brain. The reference avoids this by using apiserver-
    comparable wall time for lease renewTime; this clock goes one better
    and slaves directly to the HOST's clock, so even wall-clock skew
    between machines cancels out.

    now() = local_monotonic + offset, where offset is estimated against
    /time with a midpoint RTT correction and re-estimated every
    `resync_interval`. Between resyncs the clock advances on the local
    monotonic rate (no network call per now()); a failed resync keeps the
    previous offset — a host outage must not stop operator-local time.
    """

    def __init__(self, remote: "RemoteAPIServer", resync_interval: float = 30.0):
        # Dedicated short-timeout client: the probe runs INSIDE now(), i.e.
        # inside the operator tick loop — inheriting the 30s CRUD timeout
        # would freeze ticks for up to 30s per resync attempt during a
        # blackholed-host partition, exactly when responsiveness matters.
        # Full HA address list, not just the current base_url: after a host
        # failover the CRUD client rotates, and clock resyncs must follow it
        # to the promoted standby — probing only the dead primary would
        # freeze the offset and let leases drift toward split-brain.
        self._probe = RemoteAPIServer(
            addresses=remote.addresses, timeout=2.0, token=remote.token,
            ca_file=remote.ca_file,
        )
        self._resync_interval = resync_interval
        self._offset: Optional[float] = None
        self._last_sync = -float("inf")
        self._sync()

    def _sync(self) -> None:
        t0 = _time.monotonic()
        try:
            server_now = self._probe.server_time()
        except (ApiUnavailableError, ApiServerError, PermissionError):
            # Count the ATTEMPT as the last sync: during a host outage,
            # now() must keep running on the cached offset at local rate —
            # one failed probe per resync_interval, not a blocking network
            # call per now() (which would freeze the operator tick loop for
            # the socket timeout, per call, exactly when responsiveness to
            # the host's return matters most).
            self._last_sync = _time.monotonic()
            if self._offset is None:
                # Never synced: fall back to wall time so timestamps are at
                # least cross-machine *meaningful*; a later successful
                # resync snaps onto the host epoch.
                self._offset = _time.time() - t0
            return
        t1 = _time.monotonic()
        self._offset = server_now - (t0 + t1) / 2.0
        self._last_sync = t1

    def now(self) -> float:
        local = _time.monotonic()
        if local - self._last_sync > self._resync_interval:
            self._sync()
            local = _time.monotonic()
        return local + self._offset


class RemoteRuntime:
    """Run loop for a process whose API server lives elsewhere.

    Shape-compatible with `Cluster` for everything the operator stack and
    the SDK consume (`api`, `clock`, `add_ticker`/`remove_ticker`,
    `schedule_at`/`schedule_after`, `run_until`/`run_for`, `live`), but with
    no local store, scheduler, or kubelet — those live in the serving
    process. Always real-clock: across OS processes there is no shared
    virtual time.
    """

    def __init__(self, api: RemoteAPIServer, tick_interval: float = 0.02):
        self.api = api
        # Host-slaved time (see SyncedClock): lease and TTL arithmetic in
        # this process compares against timestamps other processes wrote.
        self.clock = SyncedClock(api)
        self.tick_interval = tick_interval
        self._tickers: List[Callable[[], None]] = []
        self._timers: List[Tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = itertools.count()
        # schedule_after is called from reconcile WORKER threads (requeue
        # backoff) while the main loop pops due timers in step(); heapq on
        # a shared list is not thread-safe, and a corrupted heap silently
        # delays or drops requeue timers. All heap mutation goes through
        # this lock; timer callbacks run OUTSIDE it (a callback that
        # schedules again must not deadlock).
        self._timers_lock = TrackedLock("wire_runtime.timers")

    def add_ticker(self, fn: Callable[[], None]) -> None:
        self._tickers.append(fn)

    def remove_ticker(self, fn: Callable[[], None]) -> None:
        try:
            self._tickers.remove(fn)
        except ValueError:
            pass

    def schedule_at(self, t: float, fn: Callable[[], None]) -> None:
        with self._timers_lock:
            heapq.heappush(self._timers, (t, next(self._timer_seq), fn))

    def schedule_after(self, dt: float, fn: Callable[[], None]) -> None:
        self.schedule_at(self.clock.now() + dt, fn)

    def live(self, obj: Any) -> Any:
        ns = getattr(obj.metadata, "namespace", "") or ""
        return self.api.try_get(obj.KIND, ns, obj.metadata.name)

    def step(self) -> None:
        now = self.clock.now()
        while True:
            with self._timers_lock:
                if not self._timers or self._timers[0][0] > now:
                    break
                _, _, fn = heapq.heappop(self._timers)
            fn()
        for fn in list(self._tickers):
            fn()

    def run_until(self, predicate: Callable[[], bool], timeout: float = 30.0) -> bool:
        deadline = self.clock.now() + timeout
        while True:
            if predicate():
                return True
            self.step()
            if predicate():
                return True
            if self.clock.now() >= deadline:
                return False
            _time.sleep(self.tick_interval)

    def run_for(self, seconds: float) -> None:
        self.run_until(lambda: False, timeout=seconds)

    def run_forever(self, stop: threading.Event) -> None:
        """Operator main loop: a transient transport failure (host restart,
        connection reset) is survived with backoff — the process must NOT
        die, or one API hiccup would take out leader and standby together.
        Leadership safety doesn't depend on this: an unrenewable lease just
        expires and the healthiest candidate re-acquires."""
        backoff = 0.1
        while not stop.is_set():
            try:
                self.step()
                backoff = 0.1
            except (ApiUnavailableError, ApiServerError) as e:
                # Transport down, or the server answered 5xx — equally
                # transient from here (k8s clients retry 500s the same
                # way). Anything else — including plain RuntimeError from
                # local code — is a bug and crashes loudly.
                log.warning("API server error (%s); retrying in %.1fs", e, backoff)
                _time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            _time.sleep(self.tick_interval)
