"""Cluster runtime: clock, timer heap, tick loop, default scheduler, kubelet.

Single-threaded and event-driven by design. Controllers, schedulers, and the
virtual kubelet register as *tickers*; each `Cluster.step()` drains due timers
then runs every ticker once. Watch events queue between ticks, which faithfully
reproduces the informer-echo asynchrony the reference's expectations cache
exists to absorb (expectation/expectation.go:29-40) while keeping every test
deterministic — the "envtest with no kubelet" strategy from SURVEY.md §4, with
the option of a real kubelet (`SimKubelet`) that actually runs pod lifecycles.
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from typing import Any, Callable, Dict, List, Optional, Tuple

from training_operator_tpu.cluster.apiserver import APIServer, SharedInformer
from training_operator_tpu.cluster.objects import (
    NODE_LEASE_NAMESPACE,
    ContainerStatus,
    Lease,
    Node,
    Pod,
    PodPhase,
    node_ready,
    tolerates,
)

ANNOTATION_SIM_DURATION = "sim.tpu.dev/run-seconds"
ANNOTATION_SIM_EXIT_CODE = "sim.tpu.dev/exit-code"
# JSON array of stdout lines the simulated container "prints" on start —
# the per-pod log model's stand-in for trainer output (real workloads
# attach theirs via SimKubelet.complete_pod(log=...)).
ANNOTATION_SIM_LOG_LINES = "sim.tpu.dev/log-lines"


class Clock:
    """Real wall clock."""

    def now(self) -> float:
        return _time.monotonic()

    def is_virtual(self) -> bool:
        return False


class WallClock(Clock):
    """Wall-clock time (`time.time()`), for the serving-host role: every
    timestamp the host persists into durable state (pod start times, lease
    renew times) must stay meaningful across a host process restart, and
    monotonic epochs die with the process. Remote operators slave to this
    clock via GET /time (httpapi.SyncedClock), so NTP steps affect all
    participants together."""

    def now(self) -> float:
        return _time.time()


class VirtualClock(Clock):
    """Manually-advanced clock for deterministic TTL/backoff/deadline tests."""

    def __init__(self, start: float = 0.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        self._now += dt

    def set(self, t: float) -> None:
        self._now = max(self._now, t)

    def is_virtual(self) -> bool:
        return True


class Cluster:
    """The substrate runtime tying APIServer + nodes + scheduler + kubelet."""

    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self.api = APIServer()
        # Timeline spans must be stamped in CLUSTER time (virtual-clock sims
        # trace in sim time; the host role's WallClock keeps them durable).
        self.api.timelines.set_clock(self.clock.now)
        # Shared read cache (controller-runtime's shared informer): synced at
        # the top of every step, read by schedulers/kubelet/benchmarks so
        # full-state scans don't clone the store each tick.
        self.informer = SharedInformer(self.api)
        # Substrate exec primitive (see ExecChannel): the MPI launchers'
        # rsh/bootstrap channel into worker pods.
        self.exec = ExecChannel(self)
        # The attached SimKubelet, if any (set by its constructor): the
        # authoritative node-liveness source for the exec channel.
        self.kubelet = None
        self._tickers: List[Callable[[], None]] = []
        self._timers: List[Tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = itertools.count()

    # -- topology ----------------------------------------------------------

    def add_nodes(self, nodes: List[Node]) -> None:
        for n in nodes:
            self.api.create(n)

    def nodes(self) -> List[Node]:
        return self.api.list("Node")

    def live(self, obj: Any) -> Any:
        """Latest stored state of `obj` (or None if deleted). With copy-on-
        read semantics a submitted object never mutates in the caller's hand
        — k8s clients re-GET, and so must tests/benchmarks."""
        ns = getattr(obj.metadata, "namespace", "") or ""
        return self.api.try_get(obj.KIND, ns, obj.metadata.name)

    # -- scheduling of work ------------------------------------------------

    def add_ticker(self, fn: Callable[[], None]) -> None:
        self._tickers.append(fn)

    def remove_ticker(self, fn: Callable[[], None]) -> None:
        """Detach a component (operator shutdown / restart simulation)."""
        try:
            self._tickers.remove(fn)
        except ValueError:
            pass

    def schedule_at(self, t: float, fn: Callable[[], None]) -> None:
        heapq.heappush(self._timers, (t, next(self._timer_seq), fn))

    def schedule_after(self, dt: float, fn: Callable[[], None]) -> None:
        self.schedule_at(self.clock.now() + dt, fn)

    def next_timer_at(self) -> Optional[float]:
        return self._timers[0][0] if self._timers else None

    def step(self) -> None:
        """One tick: sync the shared informer, run due timers, then every
        ticker once."""
        self.informer.sync()
        now = self.clock.now()
        while self._timers and self._timers[0][0] <= now:
            _, _, fn = heapq.heappop(self._timers)
            fn()
        for fn in list(self._tickers):
            fn()

    def run_until(
        self,
        predicate: Callable[[], bool],
        timeout: float = 30.0,
        max_steps: int = 1_000_000,
    ) -> bool:
        """Step until predicate holds. With a VirtualClock, idle time jumps to
        the next timer; with a real clock, idle ticks sleep briefly.

        The deadline check happens *after* stepping so a timer due exactly at
        the deadline still fires before we give up. With a VirtualClock, time
        only jumps forward when the system is quiescent (no API writes during
        the last step and no timers already due) — otherwise cascading work
        (scheduler binding -> kubelet start -> controller reconcile) would be
        skipped over by an early timer jump.
        """
        deadline = self.clock.now() + timeout
        for _ in range(max_steps):
            if predicate():
                return True
            version_before = self.api.version()
            self.step()
            if predicate():
                return True
            if self.clock.now() >= deadline:
                return False
            if isinstance(self.clock, VirtualClock):
                if self.api.version() != version_before:
                    continue  # activity this step; let cascades settle first
                nxt = self.next_timer_at()
                if nxt is None:
                    self.clock.advance(0.01)
                elif nxt > self.clock.now():
                    self.clock.set(min(nxt, deadline))
                # due timers fire on the next step at the current instant
            else:
                _time.sleep(0.0005)
        return False

    def run_for(self, seconds: float) -> None:
        end = self.clock.now() + seconds
        self.run_until(lambda: False, timeout=seconds)
        if isinstance(self.clock, VirtualClock):
            self.clock.set(end)


def request_fits(request: Dict[str, float], avail: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in request.items())


# The file the exec-agent volume materializes inside pods. MPI launchers
# point their rsh/bootstrap agent at it; in a real deployment the node agent
# backs `cluster-exec` — in the substrate, ExecChannel does.
EXEC_AGENT_SCRIPT = (
    "#!/bin/sh\n"
    "# substrate exec channel: exec-agent <host> <command...>\n"
    'exec cluster-exec "$@"\n'
)


class ExecChannel:
    """Substrate exec primitive: run a command inside a member pod.

    Replaces the reference MPI controller's kubectl-exec machinery — a
    kubectl binary smuggled in by an init container plus per-job
    Role/RoleBinding grants (mpijob_controller.go:1227-1393) — with a
    first-class runtime capability: the target must exist and be Running,
    and every invocation is recorded (`log`) so tests can assert the
    launcher actually reached its workers. No RBAC objects, no delivery
    container.
    """

    def __init__(self, cluster: "Cluster"):
        from collections import deque

        self.cluster = cluster
        # Bounded ring: long simulations with repeated launcher execs must
        # not grow memory linearly with sim length.
        self.log: "deque[Tuple[str, str, Tuple[str, ...]]]" = deque(maxlen=4096)

    def exec_in_pod(self, namespace: str, pod_name: str, argv: List[str]) -> Tuple[int, str]:
        pod = self.cluster.api.try_get("Pod", namespace, pod_name)
        if pod is None:
            return 127, f"pod {namespace}/{pod_name} not found"
        if pod.status.phase != PodPhase.RUNNING:
            return 1, f"pod {pod_name} is {pod.status.phase.value}, not Running"
        # Host-loss gate: exec into a pod whose node is dead/NotReady must
        # fail like a dropped ssh connection (255), NOT vacuously succeed —
        # MPI launchers key remote-host health on this status. Three
        # liveness sources, strongest first: the kubelet's own dead set
        # (instant truth in sims), node existence, Ready condition.
        if pod.node_name:
            kubelet = getattr(self.cluster, "kubelet", None)
            if kubelet is not None and not kubelet.node_alive(pod.node_name):
                return 255, f"node {pod.node_name} is down"
            node = self.cluster.api.try_get("Node", "", pod.node_name)
            if node is None:
                return 255, f"node {pod.node_name} no longer exists"
            if not node_ready(node):
                return 255, f"node {pod.node_name} is NotReady"
        self.log.append((namespace, pod_name, tuple(argv)))
        return 0, ""


def resolve_pod_files(api: APIServer, pod: Pod) -> Dict[str, str]:
    """Materialize a pod's mounted-file view from its volumes — the
    substrate analogue of kubelet volume mounting. Supported volume shapes
    (k8s-style dicts on PodTemplateSpec.volumes, with a `mountPath` key):

      {"name": ..., "mountPath": "/etc/mpi", "configMap": {"name": ...}}
          -> one file per ConfigMap data key under mountPath
      {"name": ..., "mountPath": "/etc/mpi", "execAgent": {}}
          -> mountPath/exec-agent backed by the cluster ExecChannel
    """
    files: Dict[str, str] = {}
    for vol in pod.spec.volumes:
        mount = str(vol.get("mountPath") or "/").rstrip("/")
        cm_ref = vol.get("configMap")
        if cm_ref:
            cm = api.try_get("ConfigMap", pod.namespace, cm_ref.get("name", ""))
            if cm is not None:
                for key, content in cm.data.items():
                    files[f"{mount}/{key}"] = content
        if "execAgent" in vol:
            files[f"{mount}/exec-agent"] = EXEC_AGENT_SCRIPT
    return files


class DefaultScheduler:
    """First-fit bind of pending pods — the reference's "default-scheduler"
    baseline (BASELINE.md config 1). Skips pods that opt into gang scheduling
    (scheduler_name set to a gang scheduler) — those are bound by the gang
    scheduler component."""

    def __init__(self, cluster: Cluster, handles_scheduler_names: Tuple[str, ...] = ("", "default-scheduler")):
        self.cluster = cluster
        self.handles = set(handles_scheduler_names)
        # Informer pattern: unbound pods, active (bound, non-terminal) pods,
        # and nodes are all maintained from THIS component's watch events, so
        # the retry gate and the capacity view can never disagree (a shared
        # cache synced elsewhere lags the events drained here, which would
        # deadlock an attempt-once gate). Initial LIST, then WATCH.
        self._watch = cluster.api.watch(kinds=("Pod", "Node"))
        self._pending: dict = {}
        self._active: dict = {}  # (ns, name) -> bound non-terminal pod
        self._nodes: dict = {}
        for pod in cluster.api.list("Pod"):
            self._observe_pod("Added", pod)
        for node in cluster.api.list("Node"):
            self._nodes[node.name] = node
        # Retry only when something changed: a new pending pod, freed
        # capacity (bound pod terminal/deleted), or a node event.
        self._dirty = True
        cluster.add_ticker(self.tick)

    def _observe_pod(self, ev_type: str, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        if (
            ev_type != "Deleted"
            and pod.status.phase == PodPhase.PENDING
            and not pod.node_name
            and pod.spec.scheduler_name in self.handles
        ):
            self._pending[key] = pod
            self._dirty = True
        else:
            self._pending.pop(key, None)
        if ev_type != "Deleted" and pod.node_name and not pod.is_terminal():
            self._active[key] = pod
        elif self._active.pop(key, None) is not None:
            self._dirty = True  # capacity freed

    def _free(self) -> Dict[str, Dict[str, float]]:
        used: Dict[str, Dict[str, float]] = {}
        for pod in self._active.values():
            bucket = used.setdefault(pod.node_name, {})
            for k, v in pod.resources().items():
                bucket[k] = bucket.get(k, 0.0) + v
        free: Dict[str, Dict[str, float]] = {}
        for node in self._nodes.values():
            if node.unschedulable or not node_ready(node):
                continue
            u = used.get(node.name, {})
            free[node.name] = {
                k: cap - u.get(k, 0.0) for k, cap in node.capacity.items()
            }
        return free

    def tick(self) -> None:
        for ev in self._watch.drain():
            if ev.kind == "Node":
                if ev.type == "Deleted":
                    self._nodes.pop(ev.obj.metadata.name, None)
                else:
                    self._nodes[ev.obj.metadata.name] = ev.obj
                self._dirty = True
            else:
                self._observe_pod(ev.type, ev.obj)
        if not self._pending or not self._dirty:
            return
        self._dirty = False
        free = self._free()
        bound = []
        for key, pod in self._pending.items():
            req = pod.resources()
            for name, node in self._nodes.items():
                if node.unschedulable or name not in free:
                    continue
                if pod.spec.node_selector and not node.matches_selector(pod.spec.node_selector):
                    continue
                if node.taints and not tolerates(node.taints, pod.spec.tolerations):
                    continue
                if request_fits(req, free[name]):
                    bind_pod(self.cluster.api, pod, name, now=self.cluster.clock.now())
                    self._active[key] = pod
                    for k, v in req.items():
                        free[name][k] = free[name].get(k, 0.0) - v
                    bound.append(key)
                    break
        for key in bound:
            self._pending.pop(key, None)


def bind_pod(api: APIServer, pod: Pod, node_name: str, now: Optional[float] = None) -> None:
    pod.node_name = node_name
    if pod.status.scheduled_time is None and now is not None:
        pod.status.scheduled_time = now
    api.update(pod, check_version=False)


class SimKubelet:
    """Virtual kubelet: starts bound pods after a latency, optionally completes
    them after an annotated duration with an annotated exit code.

    Node lifecycle duties (the kube-node-lease analogue): every
    `heartbeat_interval` the kubelet renews one Lease per live node in the
    `node-leases` namespace. `kill_node` silences a node — its heartbeat
    stops, its pods neither start nor complete (the processes died with the
    host), and detection is the node lifecycle controller's job, exactly
    like a real dead host. `recover_node` resumes the heartbeat.

    Tests that want envtest-style manual phase control simply don't attach a
    kubelet (or never annotate durations) and mutate pod phases directly.
    """

    def __init__(
        self,
        cluster: Cluster,
        start_latency: float = 0.0,
        heartbeat_interval: float = 10.0,
        heartbeats: bool = True,
    ):
        self.cluster = cluster
        self.start_latency = start_latency
        self.heartbeat_interval = heartbeat_interval
        self._dead_nodes: set = set()
        self._starting: set = set()
        # Informer pattern: newly-bound pods arrive as watch events instead
        # of a full pod scan per tick (O(events), not O(cluster x steps)).
        # Like a real informer: initial LIST, then WATCH.
        self._watch = cluster.api.watch(kinds=("Pod",))
        self._backlog = list(cluster.api.list("Pod"))
        cluster.add_ticker(self.tick)
        # The cluster's kubelet handle (ExecChannel's liveness source).
        cluster.kubelet = self
        if heartbeats:
            # First beat immediately-ish via timer (not inline: nodes may be
            # added right after construction), then every interval.
            self.cluster.schedule_after(0.0, self._heartbeat)

    # -- node liveness -----------------------------------------------------

    def dead_nodes(self) -> set:
        """Nodes this kubelet currently holds dead (heartbeats silenced) —
        worker-host death is EXTERNAL state: a control-plane host failover
        builds a fresh kubelet, and the promotion path must re-silence
        these nodes on it or the new incarnation's first heartbeat would
        resurrect every dead host's lease."""
        return set(self._dead_nodes)

    def node_alive(self, name: str) -> bool:
        return (
            bool(name)
            and name not in self._dead_nodes
            and self.cluster.api.resource_version("Node", "", name) is not None
        )

    def kill_node(self, name: str) -> None:
        """The host died: heartbeat stops, nothing on it starts or finishes.
        Pod objects keep their last written phase — a dead kubelet writes
        nothing — until the lifecycle controller evicts them."""
        self._dead_nodes.add(name)

    def recover_node(self, name: str) -> None:
        self._dead_nodes.discard(name)
        self._beat_one(name, self.cluster.clock.now())
        # Pods bound to this node that waited out the outage: re-arm starts
        # (their bind event was consumed while it was dead) and completion
        # timers (the finisher that fired during the outage no-op'd).
        for pod in self.cluster.api.list("Pod"):
            if pod.node_name != name:
                continue
            if pod.status.phase == PodPhase.PENDING:
                self._maybe_start(pod)
            elif pod.status.phase == PodPhase.RUNNING:
                self._maybe_recover(pod)

    def _beat_one(self, name: str, now: float) -> None:
        api = self.cluster.api
        lease = api.try_get("Lease", NODE_LEASE_NAMESPACE, name)
        if lease is None:
            from training_operator_tpu.api.jobs import ObjectMeta

            lease = Lease(
                metadata=ObjectMeta(name=name, namespace=NODE_LEASE_NAMESPACE),
                holder=name,
                lease_duration=self.heartbeat_interval,
                acquire_time=now,
                renew_time=now,
            )
            api.create(lease)
        else:
            lease.renew_time = now
            api.update(lease, check_version=False)

    def _heartbeat(self) -> None:
        now = self.cluster.clock.now()
        for node in self.cluster.api.list_refs("Node"):
            if node.name not in self._dead_nodes:
                self._beat_one(node.name, now)
        self.cluster.schedule_after(self.heartbeat_interval, self._heartbeat)

    # -- pod lifecycle -----------------------------------------------------

    def tick(self) -> None:
        backlog, self._backlog = self._backlog, []
        for pod in backlog:
            self._maybe_start(pod)
            self._maybe_recover(pod)
        for ev in self._watch.drain():
            if ev.type != "Deleted":
                self._maybe_start(ev.obj)

    def _maybe_recover(self, pod: Pod) -> None:
        """Re-arm the completion timer of a pod that was already RUNNING when
        this kubelet came up — the host-restart recovery path: finish timers
        are process state and die with the crashed host, but the pod objects
        (with wall-clock start times) come back from the durable store. A
        pod whose deadline passed during the outage finishes immediately."""
        if pod.status.phase != PodPhase.RUNNING:
            return
        dur = pod.spec.annotations.get(ANNOTATION_SIM_DURATION)
        if dur is None:
            return
        code = int(pod.spec.annotations.get(ANNOTATION_SIM_EXIT_CODE, "0"))
        now = self.cluster.clock.now()
        started = pod.status.start_time if pod.status.start_time is not None else now
        self.cluster.schedule_at(
            max(now, started + float(dur)),
            self._make_finisher(pod.metadata.uid, pod.namespace, pod.name, code),
        )

    def _maybe_start(self, pod: Pod) -> None:
        if (
            pod.node_name
            and pod.status.phase == PodPhase.PENDING
            and self.node_alive(pod.node_name)  # dead/vanished host: stay PENDING
            and pod.metadata.uid not in self._starting
        ):
            self._starting.add(pod.metadata.uid)
            if pod.status.scheduled_time is None:
                pod.status.scheduled_time = self.cluster.clock.now()
            self.cluster.schedule_after(
                self.start_latency,
                self._make_starter(pod.metadata.uid, pod.namespace, pod.name),
            )

    def _make_starter(self, uid: str, namespace: str, name: str):
        def start():
            pod = self.cluster.api.try_get("Pod", namespace, name)
            if pod is None or pod.metadata.uid != uid or pod.status.phase != PodPhase.PENDING:
                self._starting.discard(uid)
                return
            if not self.node_alive(pod.node_name):
                # Node died between bind and start: the pod stays PENDING
                # (recover_node re-arms it; eviction handles the rest).
                self._starting.discard(uid)
                return
            pod.status.phase = PodPhase.RUNNING
            pod.status.start_time = self.cluster.clock.now()
            pod.status.container_statuses = [
                ContainerStatus(name=c.name, running=True) for c in pod.spec.containers
            ]
            self.cluster.api.update(pod, check_version=False)
            now = self.cluster.clock.now()
            for c in pod.spec.containers:
                self.cluster.api.append_pod_log(
                    namespace, name,
                    f"Started container {c.name} on {pod.node_name}", now,
                )
            raw = pod.spec.annotations.get(ANNOTATION_SIM_LOG_LINES)
            if raw is not None:
                import json

                try:
                    for ln in json.loads(raw):
                        self.cluster.api.append_pod_log(namespace, name, str(ln), now)
                except (ValueError, TypeError):
                    pass  # a malformed sim annotation must not kill the kubelet
            self._starting.discard(uid)
            self._schedule_finish(pod, uid)

        return start

    def complete_pod(
        self, namespace: str, name: str, exit_code: int = 0,
        log: Optional[str] = None,
    ) -> bool:
        """External completion: a real workload process attached to this pod
        exited — propagate its exit code exactly as an annotated sim finish
        would (restart policy honored). This is the seam the real-process
        e2e tier uses: OS processes run the container's work, their captured
        stdout lands in the pod's log (`log`), and their exit codes flow
        back through the kubelet into pod/job status."""
        pod = self.cluster.api.try_get("Pod", namespace, name)
        if pod is None or pod.status.phase != PodPhase.RUNNING:
            return False
        if not self.node_alive(pod.node_name):
            return False  # nothing on a dead host exits with a code
        if log:
            self.cluster.api.append_pod_log(
                namespace, name, log, self.cluster.clock.now()
            )
        self._make_finisher(pod.metadata.uid, namespace, name, exit_code)()
        return True

    def _schedule_finish(self, pod: Pod, uid: str) -> None:
        """Arm the completion timer from the pod's sim annotations (if any)."""
        dur = pod.spec.annotations.get(ANNOTATION_SIM_DURATION)
        if dur is None:
            return
        code = int(pod.spec.annotations.get(ANNOTATION_SIM_EXIT_CODE, "0"))
        self.cluster.schedule_after(
            float(dur), self._make_finisher(uid, pod.namespace, pod.name, code)
        )

    def _make_finisher(self, uid: str, namespace: str, name: str, exit_code: int):
        def finish():
            pod = self.cluster.api.try_get("Pod", namespace, name)
            if pod is None or pod.metadata.uid != uid or pod.status.phase != PodPhase.RUNNING:
                return
            if not self.node_alive(pod.node_name):
                # The host (and the container's process) is gone: no exit
                # code will ever surface. Leave the stale RUNNING phase for
                # the node lifecycle controller to evict.
                return
            # Honor pod-level restart policy the way the kubelet does:
            # Always restarts in place on any exit; OnFailure on exit != 0;
            # Never (and OnFailure with exit 0) surfaces the terminal phase.
            # In-place restarts bump restart_count — the signal
            # past_backoff_limit sums (reference core/job.go:95).
            from training_operator_tpu.api.common import RestartPolicy

            policy = pod.effective_restart_policy()
            should_restart = policy == RestartPolicy.ALWAYS or (
                policy == RestartPolicy.ON_FAILURE and exit_code != 0
            )
            self.cluster.api.append_pod_log(
                namespace, name,
                f"Container exited with code {exit_code}"
                + ("; restarting" if should_restart else ""),
                self.cluster.clock.now(),
            )
            if should_restart:
                for cs in pod.status.container_statuses:
                    cs.restart_count += 1
                    cs.exit_code = exit_code
                    cs.running = True
                self.cluster.api.update(pod, check_version=False)
                self._schedule_finish(pod, uid)
                return
            mark_pod_finished(self.cluster.api, pod, exit_code, now=self.cluster.clock.now())

        return finish


def mark_pod_finished(api: APIServer, pod: Pod, exit_code: int, now: float = 0.0) -> None:
    pod.status.phase = PodPhase.SUCCEEDED if exit_code == 0 else PodPhase.FAILED
    pod.status.finish_time = now
    for cs in pod.status.container_statuses:
        cs.running = False
        cs.exit_code = exit_code
    if not pod.status.container_statuses:
        pod.status.container_statuses = [
            ContainerStatus(name=c.name, exit_code=exit_code) for c in pod.spec.containers
        ]
    api.update(pod, check_version=False)
