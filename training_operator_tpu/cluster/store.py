"""Durable host state: snapshot + write-ahead journal for the serving role.

The reference control plane never worries about apiserver durability because
etcd is durable: kill the apiserver and every job, lease, and pod record is
still there when it returns; operators simply relist and resume
(SURVEY.md §1 substrate row). The `--role host` process is this framework's
apiserver+etcd collapsed into one process, so it must supply the durability
itself — otherwise a host crash erases the cluster out from under operators
whose own retry loops (httpapi.RemoteRuntime.run_forever) survive just fine.

Design: snapshot + generation-numbered journals.

  snapshot.json        full encoded state (objects, resourceVersion counter,
                       events, pod logs) plus the journal generation it
                       covers; written atomically (tmp + fsync + rename)
  journal.<gen>.jsonl  one JSON line per mutation since that generation
                       began: put/del/event/log records, appended and
                       flushed inside the store lock so journal order IS
                       the store's write order

Compaction rotates to a fresh generation FIRST (cheap, under the API lock so
no record can fall between capture and rotation), then writes the snapshot
OUTSIDE the lock — a multi-second state encode never stalls the control
plane — and only then deletes journals the new snapshot covers. Generations
make every crash window safe:

  crash after rotation, before snapshot lands → old snapshot + both journal
      generations replay in order; nothing lost, nothing doubled
  crash after snapshot lands, before old journals are deleted → recovery
      replays only generations >= the snapshot's; the stale journal is
      ignored (and cleaned up), so append-only records (events, pod logs)
      are never applied twice

Recovery replays journals in generation order. A torn final record — the
crash landed mid-write — is detected by JSON parse failure, dropped, and
*physically truncated* from the file, so a later process appending to the
same generation can never produce a merged corrupt line that would swallow
acknowledged writes behind it.

Durability level: `flush()` per record (survives kill -9 of the host, the
failure mode HA actually exercises) + fsync on snapshot rotation. Full
power-loss fsync-per-write is deliberately not the default — it would gate
every control-plane write on disk latency, and the reference's own etcd
batches fsyncs too — but is available as the `fsync_per_record` knob
(OperatorConfig.journal_fsync / --journal-fsync). Compaction cadence and
the journal-bytes bound are knobs too: see __init__.
"""

from __future__ import annotations

import itertools as _itertools
import json
import logging
import os
import re
import threading
import time as _time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.apiserver import APIServer
from training_operator_tpu.cluster.objects import Event
from training_operator_tpu.utils import metrics
from training_operator_tpu.utils.locks import TrackedCondition, TrackedLock

log = logging.getLogger(__name__)

SNAPSHOT = "snapshot.json"
_JOURNAL_RE = re.compile(r"^journal\.(\d+)\.jsonl$")


def decode_snapshot(snap: Dict[str, Any]) -> Tuple[List[Any], int, List[Event], Dict[Tuple[str, str], Dict[str, Any]]]:
    """Decode an encode_snapshot payload back into live state:
    (objects, rv, events, pod_logs). THE inverse of
    apiserver.encode_snapshot — shared by local snapshot-file recovery
    (load_into) and the standby's replication bootstrap
    (GET /replication/snapshot), so the two cannot drift."""
    objects = [wire.decode(d) for d in snap.get("objects", [])]
    events = [wire.decode(d, Event) for d in snap.get("events", [])]
    pod_logs: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for entry in snap.get("pod_logs", []):
        pod_logs[(entry["ns"], entry["name"])] = {
            "lines": [(float(ts), ln) for ts, ln in entry["lines"]],
            "base": int(entry["base"]),
        }
    return objects, int(snap.get("rv", 0)), events, pod_logs


class JournalWriteError(RuntimeError):
    """A journal append failed (disk full, fd revoked, I/O error). The
    journal is WRITE-AHEAD (the sink runs before the in-memory apply and
    the watch notify), so the triggering API mutation aborts cleanly —
    no watcher ever observed it — but the journal file may now end in a
    torn record and the device is in an unknown state. etcd treats this as
    fatal and panics; this store does the analogue: the error propagates
    to the caller, the store latches DEGRADED (every subsequent mutation
    fails loudly, compaction refuses), and the host process exits so
    supervision restarts it from the last durable state (recovery truncates
    the torn tail). The one thing that can never happen is an acknowledged
    write silently missing from the journal."""


def journal_name(gen: int) -> str:
    return f"journal.{gen:08d}.jsonl"


class HostStore:
    """Snapshot+journal persistence attached to one APIServer.

    Usage (host boot):
        store = HostStore(state_dir)
        store.load_into(api)      # restore prior state (no-op first boot)
        store.attach(api)         # journal every subsequent mutation
        ...
        store.maybe_compact(api)  # called periodically from the host loop
    """

    def __init__(
        self,
        root: str,
        compact_every: int = 4096,
        compact_max_bytes: int = 64 * 1024 * 1024,
        fsync_per_record: bool = False,
        wal_ring: int = 65536,
    ):
        """Durability knobs (OperatorConfig.compact_every /
        .compact_max_journal_bytes / .journal_fsync + the matching CLI
        flags): compaction fires when EITHER the record count or the
        journal byte size exceeds its bound — record count alone lets a
        few huge objects grow the journal unboundedly between compacts
        (compact_max_bytes=0 disables the bytes trigger). fsync_per_record
        upgrades the per-record flush to a real fsync: survives power
        loss, not just kill -9, at the price of gating every control-plane
        write on disk latency (the reference's etcd batches fsyncs for
        the same reason — this is deliberately opt-in).

        `wal_ring` (OperatorConfig.replication_wal_ring) bounds the
        in-memory replication tail: every journaled record also lands in a
        ring served at GET /wal so a warm standby can tail the write-ahead
        log without touching disk. A standby that falls further behind
        than the ring retains re-bootstraps from a full snapshot — the
        etcd snapshot+WAL replication shape."""
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.compact_every = compact_every
        self.compact_max_bytes = compact_max_bytes
        self.fsync_per_record = fsync_per_record
        self._lock = TrackedLock("store")
        self._journal_fh = None
        self._gen = 0
        self._records_since_snapshot = 0
        self._bytes_since_snapshot = 0
        # WAL shipping state: monotonic replication seq per record, a
        # bounded ring of (seq, wall-time, record), and an epoch scoping
        # seqs to THIS store incarnation (they restart with the process; a
        # standby holding a cursor from a dead incarnation must re-
        # bootstrap, never silently resume at a colliding number).
        self.wal_ring = max(1, int(wal_ring))
        self.wal_epoch = uuid.uuid4().hex
        self._wal: "deque[Tuple[int, float, Dict[str, Any]]]" = deque()
        self._wal_seq = 0
        self._wal_floor = 0  # newest seq NOT retained (0 = nothing evicted)
        # Signalled on every WAL append so GET /wal can long-poll instead
        # of spinning; shares the store lock (waiters release it atomically).
        self._wal_cond = TrackedCondition(self._lock, name="store")
        # Torn trailing records found during replay: path -> byte offset of
        # the last whole record. Physically truncated lazily by attach()
        # (the next append), NOT during replay — replay stays read-only, so
        # recovery inspection of a crashed state dir can never itself
        # modify the evidence, and a replay-time I/O error can't refuse
        # startup (training_journal_torn_tail_total counts detections).
        self._torn_tails: Dict[str, int] = {}
        # Latched on the first journal write failure; read by the host main
        # loop, which exits rather than keep serving writes whose journal
        # records are silently missing (see JournalWriteError).
        self.degraded = False

    # -- restore -----------------------------------------------------------

    def load_into(self, api: APIServer) -> Tuple[int, int]:
        """Restore snapshot + journals into `api`; returns (objects,
        replayed journal records). Must run before `attach` and before any
        watchers besides the cluster's own SharedInformer exist — restored
        objects are announced as Added events so informers seeded at
        cluster construction converge."""
        objects: Dict[Tuple[str, str, str], Any] = {}
        events: List[Event] = []
        pod_logs: Dict[Tuple[str, str], Dict[str, Any]] = {}
        rv = 0
        snap_gen = 0

        snap_path = os.path.join(self.root, SNAPSHOT)
        if os.path.exists(snap_path):
            with open(snap_path) as f:
                snap = json.load(f)
            snap_gen = int(snap.get("gen", 0))
            decoded, rv, events, pod_logs = decode_snapshot(snap)
            for obj in decoded:
                objects[_key(obj)] = obj

        replayed = 0
        gens = self._journal_gens()
        for gen in gens:
            if gen < snap_gen:
                # The snapshot already covers this generation; the compact
                # that wrote it crashed before deleting the file. Records
                # here would double-apply (events/logs append) — skip and
                # clean up.
                os.unlink(os.path.join(self.root, journal_name(gen)))
                continue
            n, file_rv = self._replay_file(
                os.path.join(self.root, journal_name(gen)),
                objects, events, pod_logs,
            )
            replayed += n
            # del records carry the rv counter at delete time precisely so
            # a deleted-then-recreated name can never re-reach a dead
            # incarnation's version (a stale pre-crash client write would
            # then pass check_version and clobber the new object).
            rv = max(rv, file_rv)
        self._gen = max([snap_gen] + [g for g in gens if g >= snap_gen] or [0])

        # rv must also end past every restored object's version.
        for obj in objects.values():
            rv = max(rv, int(obj.metadata.resource_version or 0))

        api.restore(list(objects.values()), rv, events, pod_logs)
        if objects or replayed:
            log.info(
                "restored %d object(s) at rv=%d (+%d journal records, gen %d) from %s",
                len(objects), rv, replayed, self._gen, self.root,
            )
        return len(objects), replayed

    def _journal_gens(self) -> List[int]:
        gens = []
        for name in os.listdir(self.root):
            m = _JOURNAL_RE.match(name)
            if m:
                gens.append(int(m.group(1)))
        return sorted(gens)

    def _replay_file(self, path, objects, events, pod_logs) -> Tuple[int, int]:
        """Replay one journal file; returns (records, max rv watermark seen).
        A torn trailing record (crash mid-append — routine with
        `journal_fsync` off) stops replay cleanly at the last whole record:
        it is logged, counted in training_journal_torn_tail_total, and
        remembered for PHYSICAL truncation on the next append (attach) so a
        later process appending to the same generation can never merge with
        the fragment into one corrupt line that would hide acknowledged
        records behind it. Replay itself never refuses to start over a
        tear, and never writes."""
        replayed = 0
        max_rv = 0
        valid_end = 0
        torn = False
        with open(path, "r") as f:
            while True:
                line = f.readline()
                if not line:
                    break
                stripped = line.strip()
                if not stripped:
                    valid_end = f.tell()
                    continue
                try:
                    rec = json.loads(stripped)
                except ValueError:
                    torn = True
                    break
                if not line.endswith("\n"):
                    # Parsed, but the newline (written atomically with the
                    # record) is missing: treat as torn — the flush may not
                    # have covered the whole record.
                    torn = True
                    break
                valid_end = f.tell()
                replayed += 1
                max_rv = max(max_rv, self._apply(rec, objects, events, pod_logs))
        if torn:
            self._torn_tails[path] = valid_end
            metrics.journal_torn_tail.inc()
            log.warning(
                "%s ends in a torn record after %d whole record(s); replay "
                "stopped at byte %d (truncated on next append)",
                path, replayed, valid_end,
            )
        return replayed, max_rv

    @staticmethod
    def _apply(rec, objects, events, pod_logs) -> int:
        """Apply one record; returns the rv watermark it implies (0 = none)."""
        op = rec.get("op")
        if op == "put":
            obj = wire.decode(rec["obj"])
            objects[_key(obj)] = obj
            return int(obj.metadata.resource_version or 0)
        elif op == "del":
            objects.pop((rec["kind"], rec["ns"], rec["name"]), None)
            if rec["kind"] == "Pod":
                pod_logs.pop((rec["ns"], rec["name"]), None)
            return int(rec.get("rv", 0))
        elif op == "event":
            events.append(wire.decode(rec["event"], Event))
        elif op == "log":
            buf = pod_logs.setdefault(
                (rec["ns"], rec["name"]), {"lines": [], "base": 0}
            )
            # Same framing as APIServer.append_pod_log: the sink records
            # the original (possibly multi-line) string.
            for ln in str(rec["line"]).splitlines() or [""]:
                buf["lines"].append((float(rec["ts"]), ln))
        return 0

    # -- journal sink ------------------------------------------------------

    def _fsync_dir(self) -> None:
        """fsync the state directory: a rename (snapshot replace) or a
        newly created journal file is only durable once its directory
        entry is — without this, a power loss can reorder the metadata
        ops the crash-window analysis depends on. Best-effort on
        platforms whose directories refuse fsync."""
        try:
            fd = os.open(self.root, os.O_RDONLY)
        except OSError:  # pragma: no cover - platform-dependent
            return
        try:
            os.fsync(fd)
        except OSError:  # pragma: no cover - platform-dependent
            pass
        finally:
            os.close(fd)

    def open_journal(self) -> None:
        """Open the current-generation journal for append. A torn tail
        recorded during replay is physically truncated HERE — the moment
        before the first new append could have merged with the fragment.
        Split from attach() so a sharded plane can open every shard's
        journal while registering a single routing sink on the APIServer."""
        path = os.path.join(self.root, journal_name(self._gen))
        torn_at = self._torn_tails.pop(path, None)
        if torn_at is not None and os.path.exists(path):
            with open(path, "r+b") as f:
                f.truncate(torn_at)
            log.warning("truncated torn journal tail: %s -> %d bytes", path, torn_at)
        self._journal_fh = open(path, "a")
        # The dirent of a brand-new generation file must be durable before
        # records in it count as persisted.
        self._fsync_dir()

    def attach(self, api: APIServer) -> None:
        """Open the current-generation journal for append and register as
        the APIServer's journal sink. From here on every mutation lands in
        the journal before the API call returns (the sink runs inside the
        store lock)."""
        self.open_journal()
        api.attach_journal(self._sink)

    def _sink(self, op: str, *args: Any) -> None:
        if op == "put":
            obj = args[0]
            rec = {"op": "put", "obj": wire.encode(obj)}
            if len(args) > 1 and args[1]:
                # status_only marker: replicated watch events on a standby
                # re-announce with the same predicate the primary's did, so
                # a post-failover operator doesn't re-enqueue its own
                # status echoes (GenerationChangedPredicate parity).
                rec["so"] = 1
        elif op == "del":
            kind, ns, name, rv = args
            rec = {"op": "del", "kind": kind, "ns": ns, "name": name, "rv": rv}
        elif op == "event":
            (event,) = args
            rec = {"op": "event", "event": wire.encode(event)}
        elif op == "log":
            ns, name, line, ts = args
            rec = {"op": "log", "ns": ns, "name": name, "line": line, "ts": ts}
        else:  # pragma: no cover - defensive
            return
        with self._lock:
            if self.degraded:
                raise JournalWriteError(
                    "journal is degraded after an earlier write failure; "
                    "restart the host to recover from durable state"
                )
            fh = self._journal_fh
            if fh is None:
                return
            line = json.dumps(rec) + "\n"
            try:
                fh.write(line)
                fh.flush()
                if self.fsync_per_record:
                    # Write-ahead contract: the fsync must complete under the
                    # store lock or an acked write could be reordered past a
                    # crash (fsync_per_record is off in every latency lane).
                    # lockcheck: allow CL009 — journal order IS the write order
                    os.fsync(fh.fileno())
            except (OSError, ValueError) as e:
                # ValueError: write on a closed fd. The sink is write-ahead,
                # so the caller aborts the in-memory apply — but the journal
                # may hold a torn record and the device state is unknown.
                # Latch degraded and crash loudly rather than keep accepting
                # writes the journal can't durably order.
                self.degraded = True
                log.critical(
                    "journal write failed (%s): store is DEGRADED — "
                    "failing all writes until restart recovers from "
                    "durable state", e,
                )
                raise JournalWriteError(f"journal write failed: {e}") from e
            self._records_since_snapshot += 1
            # json.dumps defaults to ensure_ascii, so the line is pure
            # ASCII: len(line) IS the byte count — no second encode of a
            # possibly-megabyte record on the write-ahead hot path.
            self._bytes_since_snapshot += len(line)
            # Replication tail: the durably journaled record becomes
            # shippable. Appended only AFTER the append succeeded — a
            # standby must never apply a record the primary's own journal
            # does not hold.
            self._wal_seq += 1
            self._wal.append((self._wal_seq, _time.time(), rec))
            if len(self._wal) > self.wal_ring:
                evicted_seq, _, _ = self._wal.popleft()
                self._wal_floor = evicted_seq
            self._wal_cond.notify_all()

    # -- WAL shipping ------------------------------------------------------

    def wal_state(self) -> Tuple[int, str]:
        """(head seq, wal epoch) — what a snapshot bootstrap hands the
        standby as its starting cursor. Callers needing the cursor
        consistent with a state capture take api.locked() around both
        (mutators hold the api lock when the sink appends here)."""
        with self._lock:
            return self._wal_seq, self.wal_epoch

    def wal_page(
        self, after: int = 0, limit: int = 1024, timeout: float = 0.0,
    ) -> Dict[str, Any]:
        """One page of the replication tail: every retained record with
        seq > `after`, oldest first, at most `limit`. With `timeout` > 0
        an empty page long-polls on the store condition until a record
        lands or the window closes (the standby's low-lag tail without a
        spin). Response:

          {"wal_epoch": ..., "head": <newest seq>, "now": <host wall time>,
           "records": [{"s": seq, "t": wall-time, "r": record}, ...]}
          {"wal_epoch": ..., "reset": true, ...}  cursor below the ring
            floor (standby outrun) or from another incarnation — the
            standby must re-bootstrap from a full snapshot.
        """
        after = int(after)
        limit = max(1, int(limit))
        deadline = _time.monotonic() + max(0.0, float(timeout))
        with self._wal_cond:
            while True:
                if after < self._wal_floor:
                    return {
                        "wal_epoch": self.wal_epoch,
                        "head": self._wal_seq,
                        "now": _time.time(),
                        "reset": True,
                        "records": [],
                    }
                if self._wal_seq > after:
                    break
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or not self._wal_cond.wait(remaining):
                    break
            records = []
            if self._wal:
                # Ring seqs are contiguous (one +=1 per append, evictions
                # only from the left), so the first record past `after` is
                # at a computable offset — a skip-scan from the head would
                # cost O(ring) under the store lock on EVERY poll, stalling
                # the write path behind each caught-up tailer.
                start = max(0, after - self._wal[0][0] + 1)
                for seq, t, rec in _itertools.islice(
                    self._wal, start, start + limit
                ):
                    records.append({"s": seq, "t": t, "r": rec})
            return {
                "wal_epoch": self.wal_epoch,
                "head": self._wal_seq,
                "now": _time.time(),
                "records": records,
            }

    def wal_ring_len(self) -> int:
        """Records currently retained in the replication WAL ring (bounded
        by `wal_ring`) — the INV009 accumulator feed."""
        with self._lock:
            return len(self._wal)

    def journal_bytes(self) -> int:
        """Bytes appended to the current journal generation since the last
        snapshot — the fleet plane's INV005 feed (a value persistently over
        `compact_max_bytes` means compaction is wedged)."""
        with self._lock:
            return self._bytes_since_snapshot

    def journal_records(self) -> int:
        with self._lock:
            return self._records_since_snapshot

    # -- compaction --------------------------------------------------------

    def maybe_compact(self, api: APIServer) -> bool:
        """Rotate journal into a fresh snapshot once enough has
        accumulated — by record count OR by journal bytes, whichever bound
        trips first (a handful of megabyte-scale objects must not grow the
        journal unboundedly while the record counter idles). Called from
        the host main loop (never a handler thread)."""
        with self._lock:
            if self.degraded:
                return False
            due = self._records_since_snapshot >= self.compact_every or (
                self.compact_max_bytes
                and self._bytes_since_snapshot >= self.compact_max_bytes
            )
            if not due:
                return False
        self.compact(api)
        return True

    def compact(self, api: APIServer) -> None:
        """Capture state and rotate the journal generation under the API
        lock (both cheap: snapshot_refs grabs references, not encodings),
        then ENCODE and write the snapshot OUTSIDE it — the multi-second
        wire-encode+fsync of a large state must not stall every concurrent
        API request. Crash windows are covered by the generation scheme
        (see module docstring)."""
        from training_operator_tpu.cluster.apiserver import encode_snapshot

        # Lock order everywhere is api lock -> store lock (mutating writers
        # hold the api lock when the sink takes the store lock).
        with api.locked():
            refs = api.snapshot_refs()
            with self._lock:
                if self.degraded:
                    # The journal device is in an unknown state (the failed
                    # append may sit as a torn record); rotating generations
                    # and fsyncing a snapshot on it is exactly the wrong
                    # moment. Recovery after restart handles the torn tail.
                    # Holding both locks makes this check race-free against
                    # a concurrent sink failure.
                    log.error("store degraded: refusing to compact")
                    return
                new_gen = self._gen + 1
                if self._journal_fh is not None:
                    try:
                        self._journal_fh.close()
                    except OSError:
                        # Every record was flush()ed at append time, so the
                        # close has nothing buffered — a failure here is
                        # inert for data, and must not crash the host
                        # outside the curated degraded path (see close()).
                        log.error("journal close failed during compaction",
                                  exc_info=True)
                self._journal_fh = open(
                    os.path.join(self.root, journal_name(new_gen)), "a"
                )
                old_gen, self._gen = self._gen, new_gen
                self._records_since_snapshot = 0
                self._bytes_since_snapshot = 0
        # The fresh generation's dirent must be durable BEFORE old journals
        # become deletable: without it a power loss could surface the
        # unlinks but not the new file — acknowledged records gone.
        self._fsync_dir()
        snap = encode_snapshot(refs)
        snap["gen"] = self._gen  # journals >= this gen are NOT in the snapshot
        self._write_snapshot_file(snap)
        # Only after the snapshot (and its rename) durably cover them:
        for gen in self._journal_gens():
            if gen <= old_gen:
                try:
                    os.unlink(os.path.join(self.root, journal_name(gen)))
                except OSError:
                    pass
        log.info(
            "compacted state into %s (gen %d)",
            os.path.join(self.root, SNAPSHOT), self._gen,
        )

    def _write_snapshot_file(self, snap: Dict[str, Any]) -> None:
        """Crash-safe snapshot install: temp file, fsync the DATA, atomic
        rename, then fsync the DIRECTORY — the rename itself is a metadata
        op, and old-journal deletion (the caller's next step) must never
        become durable before it. A crash anywhere in this sequence leaves
        either the old snapshot + all journals, or the new snapshot + all
        journals: never neither."""
        tmp = os.path.join(self.root, SNAPSHOT + ".tmp")
        with open(tmp, "w") as f:
            json.dump(snap, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, SNAPSHOT))
        self._fsync_dir()

    def adopt_snapshot(self, snap: Dict[str, Any]) -> None:
        """Standby bootstrap: install a snapshot FETCHED from the primary
        (GET /replication/snapshot) as this store's durable base, rotating
        to a fresh journal generation for the WAL records that will follow
        it. Existing local state (a previous standby term's snapshot and
        journals) is superseded wholesale — the primary's state is the
        truth, and mixing generations across bootstraps could double-apply
        append-only records. Call before attach()."""
        with self._lock:
            if self._journal_fh is not None:
                try:
                    self._journal_fh.close()
                except OSError:
                    log.error("journal close failed during adopt", exc_info=True)
                self._journal_fh = None
            old_gens = self._journal_gens()
            self._gen = (max(old_gens) if old_gens else self._gen) + 1
            self._records_since_snapshot = 0
            self._bytes_since_snapshot = 0
            self._torn_tails.clear()
            gen = self._gen
        installed = dict(snap)
        installed["gen"] = gen
        self._write_snapshot_file(installed)
        for g in old_gens:
            try:
                os.unlink(os.path.join(self.root, journal_name(g)))
            except OSError:
                pass
        log.info("adopted primary snapshot at rv=%s (gen %d) into %s",
                 snap.get("rv"), gen, self.root)

    def abandon(self) -> None:
        """SIGKILL semantics for in-process chaos (HostChaos): drop the
        journal fd without a graceful close. Records already appended are
        on their way to disk (the sink flushes per record — the documented
        kill -9 durability level); anything a crash would not have
        persisted stays unpersisted. The degraded latch makes any
        straggler write raise JournalWriteError rather than silently
        applying unjournaled — a dead process accepts no writes."""
        with self._lock:
            self._journal_fh = None
            self.degraded = True

    def close(self) -> None:
        with self._lock:
            if self._journal_fh is not None:
                try:
                    self._journal_fh.close()
                except OSError:
                    # Closing flushes; on a degraded store (ENOSPC) that can
                    # fail again — the clean degraded exit must not turn
                    # into an unhandled traceback in the shutdown path.
                    log.error("journal close failed (store degraded?)", exc_info=True)
                self._journal_fh = None


def _key(obj: Any) -> Tuple[str, str, str]:
    ns = getattr(obj.metadata, "namespace", "") or ""
    return (obj.KIND, ns, obj.metadata.name)
