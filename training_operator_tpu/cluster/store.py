"""Durable host state: snapshot + write-ahead journal for the serving role.

The reference control plane never worries about apiserver durability because
etcd is durable: kill the apiserver and every job, lease, and pod record is
still there when it returns; operators simply relist and resume
(SURVEY.md §1 substrate row). The `--role host` process is this framework's
apiserver+etcd collapsed into one process, so it must supply the durability
itself — otherwise a host crash erases the cluster out from under operators
whose own retry loops (httpapi.RemoteRuntime.run_forever) survive just fine.

Design: snapshot + generation-numbered journals.

  snapshot.json        full encoded state (objects, resourceVersion counter,
                       events, pod logs) plus the journal generation it
                       covers; written atomically (tmp + fsync + rename)
  journal.<gen>.jsonl  one JSON line per mutation since that generation
                       began: put/del/event/log records, appended and
                       flushed inside the store lock so journal order IS
                       the store's write order

Compaction rotates to a fresh generation FIRST (cheap, under the API lock so
no record can fall between capture and rotation), then writes the snapshot
OUTSIDE the lock — a multi-second state encode never stalls the control
plane — and only then deletes journals the new snapshot covers. Generations
make every crash window safe:

  crash after rotation, before snapshot lands → old snapshot + both journal
      generations replay in order; nothing lost, nothing doubled
  crash after snapshot lands, before old journals are deleted → recovery
      replays only generations >= the snapshot's; the stale journal is
      ignored (and cleaned up), so append-only records (events, pod logs)
      are never applied twice

Recovery replays journals in generation order. A torn final record — the
crash landed mid-write — is detected by JSON parse failure, dropped, and
*physically truncated* from the file, so a later process appending to the
same generation can never produce a merged corrupt line that would swallow
acknowledged writes behind it.

Durability level: `flush()` per record (survives kill -9 of the host, the
failure mode HA actually exercises) + fsync on snapshot rotation. Full
power-loss fsync-per-write is deliberately not the default — it would gate
every control-plane write on disk latency, and the reference's own etcd
batches fsyncs too — but is available as the `fsync_per_record` knob
(OperatorConfig.journal_fsync / --journal-fsync). Compaction cadence and
the journal-bytes bound are knobs too: see __init__.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.apiserver import APIServer
from training_operator_tpu.cluster.objects import Event

log = logging.getLogger(__name__)

SNAPSHOT = "snapshot.json"
_JOURNAL_RE = re.compile(r"^journal\.(\d+)\.jsonl$")


class JournalWriteError(RuntimeError):
    """A journal append failed (disk full, fd revoked, I/O error). The
    journal is WRITE-AHEAD (the sink runs before the in-memory apply and
    the watch notify), so the triggering API mutation aborts cleanly —
    no watcher ever observed it — but the journal file may now end in a
    torn record and the device is in an unknown state. etcd treats this as
    fatal and panics; this store does the analogue: the error propagates
    to the caller, the store latches DEGRADED (every subsequent mutation
    fails loudly, compaction refuses), and the host process exits so
    supervision restarts it from the last durable state (recovery truncates
    the torn tail). The one thing that can never happen is an acknowledged
    write silently missing from the journal."""


def journal_name(gen: int) -> str:
    return f"journal.{gen:08d}.jsonl"


class HostStore:
    """Snapshot+journal persistence attached to one APIServer.

    Usage (host boot):
        store = HostStore(state_dir)
        store.load_into(api)      # restore prior state (no-op first boot)
        store.attach(api)         # journal every subsequent mutation
        ...
        store.maybe_compact(api)  # called periodically from the host loop
    """

    def __init__(
        self,
        root: str,
        compact_every: int = 4096,
        compact_max_bytes: int = 64 * 1024 * 1024,
        fsync_per_record: bool = False,
    ):
        """Durability knobs (OperatorConfig.compact_every /
        .compact_max_journal_bytes / .journal_fsync + the matching CLI
        flags): compaction fires when EITHER the record count or the
        journal byte size exceeds its bound — record count alone lets a
        few huge objects grow the journal unboundedly between compacts
        (compact_max_bytes=0 disables the bytes trigger). fsync_per_record
        upgrades the per-record flush to a real fsync: survives power
        loss, not just kill -9, at the price of gating every control-plane
        write on disk latency (the reference's etcd batches fsyncs for
        the same reason — this is deliberately opt-in)."""
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.compact_every = compact_every
        self.compact_max_bytes = compact_max_bytes
        self.fsync_per_record = fsync_per_record
        self._lock = threading.Lock()
        self._journal_fh = None
        self._gen = 0
        self._records_since_snapshot = 0
        self._bytes_since_snapshot = 0
        # Latched on the first journal write failure; read by the host main
        # loop, which exits rather than keep serving writes whose journal
        # records are silently missing (see JournalWriteError).
        self.degraded = False

    # -- restore -----------------------------------------------------------

    def load_into(self, api: APIServer) -> Tuple[int, int]:
        """Restore snapshot + journals into `api`; returns (objects,
        replayed journal records). Must run before `attach` and before any
        watchers besides the cluster's own SharedInformer exist — restored
        objects are announced as Added events so informers seeded at
        cluster construction converge."""
        objects: Dict[Tuple[str, str, str], Any] = {}
        events: List[Event] = []
        pod_logs: Dict[Tuple[str, str], Dict[str, Any]] = {}
        rv = 0
        snap_gen = 0

        snap_path = os.path.join(self.root, SNAPSHOT)
        if os.path.exists(snap_path):
            with open(snap_path) as f:
                snap = json.load(f)
            rv = int(snap.get("rv", 0))
            snap_gen = int(snap.get("gen", 0))
            for data in snap.get("objects", []):
                obj = wire.decode(data)
                objects[_key(obj)] = obj
            for data in snap.get("events", []):
                events.append(wire.decode(data, Event))
            for entry in snap.get("pod_logs", []):
                pod_logs[(entry["ns"], entry["name"])] = {
                    "lines": [(float(ts), ln) for ts, ln in entry["lines"]],
                    "base": int(entry["base"]),
                }

        replayed = 0
        gens = self._journal_gens()
        for gen in gens:
            if gen < snap_gen:
                # The snapshot already covers this generation; the compact
                # that wrote it crashed before deleting the file. Records
                # here would double-apply (events/logs append) — skip and
                # clean up.
                os.unlink(os.path.join(self.root, journal_name(gen)))
                continue
            n, file_rv = self._replay_file(
                os.path.join(self.root, journal_name(gen)),
                objects, events, pod_logs,
            )
            replayed += n
            # del records carry the rv counter at delete time precisely so
            # a deleted-then-recreated name can never re-reach a dead
            # incarnation's version (a stale pre-crash client write would
            # then pass check_version and clobber the new object).
            rv = max(rv, file_rv)
        self._gen = max([snap_gen] + [g for g in gens if g >= snap_gen] or [0])

        # rv must also end past every restored object's version.
        for obj in objects.values():
            rv = max(rv, int(obj.metadata.resource_version or 0))

        api.restore(list(objects.values()), rv, events, pod_logs)
        if objects or replayed:
            log.info(
                "restored %d object(s) at rv=%d (+%d journal records, gen %d) from %s",
                len(objects), rv, replayed, self._gen, self.root,
            )
        return len(objects), replayed

    def _journal_gens(self) -> List[int]:
        gens = []
        for name in os.listdir(self.root):
            m = _JOURNAL_RE.match(name)
            if m:
                gens.append(int(m.group(1)))
        return sorted(gens)

    def _replay_file(self, path, objects, events, pod_logs) -> Tuple[int, int]:
        """Replay one journal file; returns (records, max rv watermark seen).
        Truncates a torn trailing record so a future append to the same
        generation cannot merge with the fragment into one corrupt line
        that would hide later records."""
        replayed = 0
        max_rv = 0
        valid_end = 0
        torn = False
        with open(path, "r+") as f:
            while True:
                line = f.readline()
                if not line:
                    break
                stripped = line.strip()
                if not stripped:
                    valid_end = f.tell()
                    continue
                try:
                    rec = json.loads(stripped)
                except ValueError:
                    torn = True
                    break
                if not line.endswith("\n"):
                    # Parsed, but the newline (written atomically with the
                    # record) is missing: treat as torn — the flush may not
                    # have covered the whole record.
                    torn = True
                    break
                valid_end = f.tell()
                replayed += 1
                max_rv = max(max_rv, self._apply(rec, objects, events, pod_logs))
            if torn:
                f.truncate(valid_end)
                log.warning(
                    "%s ended in a torn record; truncated to %d bytes",
                    path, valid_end,
                )
        return replayed, max_rv

    @staticmethod
    def _apply(rec, objects, events, pod_logs) -> int:
        """Apply one record; returns the rv watermark it implies (0 = none)."""
        op = rec.get("op")
        if op == "put":
            obj = wire.decode(rec["obj"])
            objects[_key(obj)] = obj
            return int(obj.metadata.resource_version or 0)
        elif op == "del":
            objects.pop((rec["kind"], rec["ns"], rec["name"]), None)
            if rec["kind"] == "Pod":
                pod_logs.pop((rec["ns"], rec["name"]), None)
            return int(rec.get("rv", 0))
        elif op == "event":
            events.append(wire.decode(rec["event"], Event))
        elif op == "log":
            buf = pod_logs.setdefault(
                (rec["ns"], rec["name"]), {"lines": [], "base": 0}
            )
            # Same framing as APIServer.append_pod_log: the sink records
            # the original (possibly multi-line) string.
            for ln in str(rec["line"]).splitlines() or [""]:
                buf["lines"].append((float(rec["ts"]), ln))
        return 0

    # -- journal sink ------------------------------------------------------

    def attach(self, api: APIServer) -> None:
        """Open the current-generation journal for append and register as
        the APIServer's journal sink. From here on every mutation lands in
        the journal before the API call returns (the sink runs inside the
        store lock)."""
        self._journal_fh = open(
            os.path.join(self.root, journal_name(self._gen)), "a"
        )
        api.attach_journal(self._sink)

    def _sink(self, op: str, *args: Any) -> None:
        if op == "put":
            (obj,) = args
            rec = {"op": "put", "obj": wire.encode(obj)}
        elif op == "del":
            kind, ns, name, rv = args
            rec = {"op": "del", "kind": kind, "ns": ns, "name": name, "rv": rv}
        elif op == "event":
            (event,) = args
            rec = {"op": "event", "event": wire.encode(event)}
        elif op == "log":
            ns, name, line, ts = args
            rec = {"op": "log", "ns": ns, "name": name, "line": line, "ts": ts}
        else:  # pragma: no cover - defensive
            return
        with self._lock:
            if self.degraded:
                raise JournalWriteError(
                    "journal is degraded after an earlier write failure; "
                    "restart the host to recover from durable state"
                )
            fh = self._journal_fh
            if fh is None:
                return
            line = json.dumps(rec) + "\n"
            try:
                fh.write(line)
                fh.flush()
                if self.fsync_per_record:
                    os.fsync(fh.fileno())
            except (OSError, ValueError) as e:
                # ValueError: write on a closed fd. The sink is write-ahead,
                # so the caller aborts the in-memory apply — but the journal
                # may hold a torn record and the device state is unknown.
                # Latch degraded and crash loudly rather than keep accepting
                # writes the journal can't durably order.
                self.degraded = True
                log.critical(
                    "journal write failed (%s): store is DEGRADED — "
                    "failing all writes until restart recovers from "
                    "durable state", e,
                )
                raise JournalWriteError(f"journal write failed: {e}") from e
            self._records_since_snapshot += 1
            # json.dumps defaults to ensure_ascii, so the line is pure
            # ASCII: len(line) IS the byte count — no second encode of a
            # possibly-megabyte record on the write-ahead hot path.
            self._bytes_since_snapshot += len(line)

    def journal_bytes(self) -> int:
        """Bytes appended to the current journal generation since the last
        snapshot — the fleet plane's INV005 feed (a value persistently over
        `compact_max_bytes` means compaction is wedged)."""
        with self._lock:
            return self._bytes_since_snapshot

    def journal_records(self) -> int:
        with self._lock:
            return self._records_since_snapshot

    # -- compaction --------------------------------------------------------

    def maybe_compact(self, api: APIServer) -> bool:
        """Rotate journal into a fresh snapshot once enough has
        accumulated — by record count OR by journal bytes, whichever bound
        trips first (a handful of megabyte-scale objects must not grow the
        journal unboundedly while the record counter idles). Called from
        the host main loop (never a handler thread)."""
        with self._lock:
            if self.degraded:
                return False
            due = self._records_since_snapshot >= self.compact_every or (
                self.compact_max_bytes
                and self._bytes_since_snapshot >= self.compact_max_bytes
            )
            if not due:
                return False
        self.compact(api)
        return True

    def compact(self, api: APIServer) -> None:
        """Capture state and rotate the journal generation under the API
        lock (both cheap: snapshot_refs grabs references, not encodings),
        then ENCODE and write the snapshot OUTSIDE it — the multi-second
        wire-encode+fsync of a large state must not stall every concurrent
        API request. Crash windows are covered by the generation scheme
        (see module docstring)."""
        from training_operator_tpu.cluster.apiserver import encode_snapshot

        # Lock order everywhere is api lock -> store lock (mutating writers
        # hold the api lock when the sink takes the store lock).
        with api.locked():
            refs = api.snapshot_refs()
            with self._lock:
                if self.degraded:
                    # The journal device is in an unknown state (the failed
                    # append may sit as a torn record); rotating generations
                    # and fsyncing a snapshot on it is exactly the wrong
                    # moment. Recovery after restart handles the torn tail.
                    # Holding both locks makes this check race-free against
                    # a concurrent sink failure.
                    log.error("store degraded: refusing to compact")
                    return
                new_gen = self._gen + 1
                if self._journal_fh is not None:
                    try:
                        self._journal_fh.close()
                    except OSError:
                        # Every record was flush()ed at append time, so the
                        # close has nothing buffered — a failure here is
                        # inert for data, and must not crash the host
                        # outside the curated degraded path (see close()).
                        log.error("journal close failed during compaction",
                                  exc_info=True)
                self._journal_fh = open(
                    os.path.join(self.root, journal_name(new_gen)), "a"
                )
                old_gen, self._gen = self._gen, new_gen
                self._records_since_snapshot = 0
                self._bytes_since_snapshot = 0
        snap = encode_snapshot(refs)
        snap["gen"] = self._gen  # journals >= this gen are NOT in the snapshot

        tmp = os.path.join(self.root, SNAPSHOT + ".tmp")
        with open(tmp, "w") as f:
            json.dump(snap, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, SNAPSHOT))
        # Only after the snapshot durably covers them:
        for gen in self._journal_gens():
            if gen <= old_gen:
                try:
                    os.unlink(os.path.join(self.root, journal_name(gen)))
                except OSError:
                    pass
        log.info(
            "compacted state into %s (gen %d)",
            os.path.join(self.root, SNAPSHOT), self._gen,
        )

    def close(self) -> None:
        with self._lock:
            if self._journal_fh is not None:
                try:
                    self._journal_fh.close()
                except OSError:
                    # Closing flushes; on a degraded store (ENOSPC) that can
                    # fail again — the clean degraded exit must not turn
                    # into an unhandled traceback in the shutdown path.
                    log.error("journal close failed (store degraded?)", exc_info=True)
                self._journal_fh = None


def _key(obj: Any) -> Tuple[str, str, str]:
    ns = getattr(obj.metadata, "namespace", "") or ""
    return (obj.KIND, ns, obj.metadata.name)
