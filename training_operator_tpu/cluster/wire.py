"""Wire codec: JSON-serializable form of every stored API object kind.

The reference talks JSON over real process boundaries everywhere — SDK ->
apiserver REST (sdk/python/kubeflow/training/api/training_client.py:41),
operator -> apiserver watch streams, webhook admission over HTTPS
(cmd/training-operator.v1/main.go:134-166). This module is the serialization
half of that boundary for the TPU-native substrate: a generic, type-driven
codec over the dataclass object model, so the HTTP API server
(cluster/httpapi.py) and remote clients exchange exactly the objects the
in-process APIServer stores.

Design: instead of hand-written to_dict/from_dict per class (the reference's
generated zz_generated deepcopy/openapi machinery), one recursive codec walks
`dataclasses.fields` + `typing.get_type_hints`:

  encode: dataclass -> {field: encode(value)}, Enum -> .value,
          list/tuple -> list, dict -> {key: encode(value)}
  decode: driven by the declared field type — Optional[X], List[X],
          Dict[str, X], nested dataclasses, Enums; `Any` passes through.

Top-level objects carry a `"kind"` discriminator resolved via KIND_REGISTRY.
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, List, Optional, Type

from training_operator_tpu.api import jobs as jobs_api
from training_operator_tpu.cluster import objects as cluster_objects
from training_operator_tpu.runtime import api as runtime_api

# kind string -> class, for every kind the APIServer can store (plus Event,
# which travels via the events subresource).
KIND_REGISTRY: Dict[str, type] = {
    cls.KIND: cls
    for cls in (
        cluster_objects.Pod,
        cluster_objects.Service,
        cluster_objects.Node,
        cluster_objects.PodGroup,
        cluster_objects.ConfigMap,
        cluster_objects.HorizontalPodAutoscaler,
        cluster_objects.Lease,
        cluster_objects.Event,
        jobs_api.JAXJob,
        jobs_api.PyTorchJob,
        jobs_api.TFJob,
        jobs_api.XGBoostJob,
        jobs_api.PaddleJob,
        jobs_api.MPIJob,
        runtime_api.TrainJob,
        runtime_api.TrainingRuntime,
        runtime_api.ClusterTrainingRuntime,
    )
}

# Resolved type hints are cached per class: get_type_hints re-evaluates the
# stringified `from __future__ import annotations` annotations on every call.
_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    cached = _HINTS_CACHE.get(cls)
    if cached is None:
        cached = typing.get_type_hints(cls)
        _HINTS_CACHE[cls] = cached
    return cached


def encode(obj: Any) -> Any:
    """Recursively encode a model value to JSON-compatible data."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {f.name: encode(getattr(obj, f.name)) for f in dataclasses.fields(obj)}
        kind = getattr(type(obj), "KIND", None)
        if kind in KIND_REGISTRY:
            out["kind"] = kind
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    return obj  # str/int/float/bool/None


def decode(data: Dict[str, Any], cls: Optional[type] = None) -> Any:
    """Decode a wire dict back into a model object.

    `cls` overrides the kind lookup (for nested calls); top-level callers
    normally rely on the `"kind"` discriminator.
    """
    if cls is None:
        kind = data.get("kind")
        cls = KIND_REGISTRY.get(kind or "")
        if cls is None:
            raise ValueError(f"unknown wire kind {kind!r}")
    return _decode_dataclass(data, cls)


def _decode_dataclass(data: Dict[str, Any], cls: type) -> Any:
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _decode_value(data[f.name], hints.get(f.name, Any))
    return cls(**kwargs)


def _decode_value(value: Any, hint: Any) -> Any:
    if value is None:
        return None
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        # Optional[X] and small unions: decode to the first non-None arm
        # that is a structured type; primitives pass through.
        for arm in typing.get_args(hint):
            if arm is type(None):
                continue
            return _decode_value(value, arm)
        return value
    if origin in (list, tuple):
        args = typing.get_args(hint)
        elem = args[0] if args else Any
        return [_decode_value(v, elem) for v in value]
    if origin is dict:
        args = typing.get_args(hint)
        val_t = args[1] if len(args) == 2 else Any
        return {k: _decode_value(v, val_t) for k, v in value.items()}
    if isinstance(hint, type):
        if dataclasses.is_dataclass(hint):
            return _decode_dataclass(value, hint)
        if issubclass(hint, enum.Enum):
            return hint(value)
        if hint is float and isinstance(value, int):
            return float(value)
    return value


def encode_watch_event(ev) -> Dict[str, Any]:
    return {
        "type": ev.type,
        "kind": ev.kind,
        "status_only": ev.status_only,
        "object": encode(ev.obj),
    }


def decode_watch_event(d: Dict[str, Any]):
    from training_operator_tpu.cluster.apiserver import WatchEvent

    return WatchEvent(
        type=d["type"],
        kind=d["kind"],
        obj=decode(d["object"]),
        status_only=bool(d.get("status_only", False)),
    )
