"""Wire codec: JSON-serializable form of every stored API object kind.

The reference talks JSON over real process boundaries everywhere — SDK ->
apiserver REST (sdk/python/kubeflow/training/api/training_client.py:41),
operator -> apiserver watch streams, webhook admission over HTTPS
(cmd/training-operator.v1/main.go:134-166). This module is the serialization
half of that boundary for the TPU-native substrate: a generic, type-driven
codec over the dataclass object model, so the HTTP API server
(cluster/httpapi.py) and remote clients exchange exactly the objects the
in-process APIServer stores.

Design: the codec is COMPILED, not interpreted. The first encode/decode of a
dataclass walks `dataclasses.fields` + `typing.get_type_hints` once and
builds a field table of closures — one encoder/decoder per field, specialized
to the declared type (Optional[X], List[X], Dict[str, X], nested dataclasses,
Enums; `Any` falls back to a value-driven walk). Every later call runs the
table: no typing-module reflection on the hot path. The wire path is the
dominant per-job control-plane cost at 1k-job-burst scale, and profile showed
the per-field hint walks were most of it.

The original reflection codec survives as `reflect_encode`/`reflect_decode`:
it is the executable spec the compiled codec is property-tested against
(tests/test_wire_fastpath.py), and the fallback for non-dataclass values.

`encode_watch_event_bytes` serializes a watch event to JSON bytes ONCE and
caches them on the (shared, immutable) event object, so N watch sessions
draining the same event reuse one serialization — the serialize-once fanout
half of the wire fast path. Cache traffic is observable via the
`training_wire_*` counters (utils/metrics.py) so benchmarks and tests can
assert hit rates instead of trusting the implementation.

Top-level objects carry a `"kind"` discriminator resolved via KIND_REGISTRY.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import threading
import typing
from typing import Any, Callable, Dict, Optional

from training_operator_tpu.api import jobs as jobs_api
from training_operator_tpu.cluster import objects as cluster_objects
from training_operator_tpu.observe import slo as slo_api
from training_operator_tpu.runtime import api as runtime_api
from training_operator_tpu.tenancy import api as tenancy_api
from training_operator_tpu.utils.locks import TrackedLock
from training_operator_tpu.utils import metrics

# kind string -> class, for every kind the APIServer can store (plus Event,
# which travels via the events subresource).
KIND_REGISTRY: Dict[str, type] = {
    cls.KIND: cls
    for cls in (
        cluster_objects.Pod,
        cluster_objects.Service,
        cluster_objects.Node,
        cluster_objects.PodGroup,
        cluster_objects.ConfigMap,
        cluster_objects.HorizontalPodAutoscaler,
        cluster_objects.Lease,
        cluster_objects.Event,
        jobs_api.JAXJob,
        jobs_api.PyTorchJob,
        jobs_api.TFJob,
        jobs_api.XGBoostJob,
        jobs_api.PaddleJob,
        jobs_api.MPIJob,
        runtime_api.TrainJob,
        runtime_api.TrainingRuntime,
        runtime_api.ClusterTrainingRuntime,
        tenancy_api.PriorityClass,
        tenancy_api.ClusterQueue,
        slo_api.SLOPolicy,
    )
}

# Compiled codec tables: dataclass -> closure. Reads are lock-free dict
# lookups; compilation (rare: once per class per process) is serialized so
# the compile counter stays exact.
_ENCODERS: Dict[type, Callable[[Any], Dict[str, Any]]] = {}
_DECODERS: Dict[type, Callable[[Dict[str, Any]], Any]] = {}
_codec_lock = TrackedLock("wire.codec")

# Resolved type hints are cached per class: get_type_hints re-evaluates the
# stringified `from __future__ import annotations` annotations on every call.
_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    cached = _HINTS_CACHE.get(cls)
    if cached is None:
        cached = typing.get_type_hints(cls)
        _HINTS_CACHE[cls] = cached
    return cached


# ---------------------------------------------------------------------------
# Compiled encoder
# ---------------------------------------------------------------------------


def _encode_value(obj: Any) -> Any:
    """Value-driven encode for `Any`-typed fields and non-dataclass input:
    the shape of the data, not a declared hint, decides. Dataclasses still
    route through their compiled encoders."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _encoder_for(type(obj))(obj)
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [_encode_value(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): _encode_value(v) for k, v in obj.items()}
    return obj  # str/int/float/bool/None


def _enc_scalar(v: Any) -> Any:
    # Declared-primitive fields occasionally hold richer values (a str-Enum
    # assigned to a str field); the type check keeps those lossless while
    # staying a single dict-free probe on the fast path.
    if v is None or type(v) in (str, int, float, bool):
        return v
    return _encode_value(v)


def _enc_dataclass_field(v: Any) -> Any:
    """Encoder for a field declared as a dataclass: dispatch on the VALUE's
    class (subclasses carry their own fields) via the compiled table. One
    shared function — the declared hint carries no extra information here."""
    if v is None:
        return None
    cls = type(v)
    if not dataclasses.is_dataclass(cls):
        return _encode_value(v)
    e = _ENCODERS.get(cls)
    if e is None:
        e = _encoder_for(cls)
    return e(v)


def _value_encoder(hint: Any) -> Callable[[Any], Any]:
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        arms = [a for a in typing.get_args(hint) if a is not type(None)]
        inner = _value_encoder(arms[0]) if len(arms) == 1 else _encode_value
        return lambda v: None if v is None else inner(v)
    if origin in (list, tuple):
        args = typing.get_args(hint)
        inner = _value_encoder(args[0]) if args else _encode_value
        return lambda v: None if v is None else [inner(x) for x in v]
    if origin is dict:
        args = typing.get_args(hint)
        inner = _value_encoder(args[1]) if len(args) == 2 else _encode_value
        return (
            lambda v: None
            if v is None
            else {str(k): inner(x) for k, x in v.items()}
        )
    if isinstance(hint, type):
        if dataclasses.is_dataclass(hint):
            return _enc_dataclass_field
        if issubclass(hint, enum.Enum):
            return lambda v: v.value if isinstance(v, enum.Enum) else v
        if hint in (str, int, float, bool):
            return _enc_scalar
    return _encode_value


def _compile_encoder(cls: type) -> Callable[[Any], Dict[str, Any]]:
    hints = _hints(cls)
    steps = tuple(
        (f.name, _value_encoder(hints.get(f.name, Any)))
        for f in dataclasses.fields(cls)
    )
    kind = getattr(cls, "KIND", None)
    if kind in KIND_REGISTRY:

        def enc(obj: Any, _steps=steps, _kind=kind) -> Dict[str, Any]:
            out = {name: fe(getattr(obj, name)) for name, fe in _steps}
            out["kind"] = _kind
            return out

    else:

        def enc(obj: Any, _steps=steps) -> Dict[str, Any]:
            return {name: fe(getattr(obj, name)) for name, fe in _steps}

    return enc


def _encoder_for(cls: type) -> Callable[[Any], Dict[str, Any]]:
    enc = _ENCODERS.get(cls)
    if enc is None:
        with _codec_lock:
            enc = _ENCODERS.get(cls)
            if enc is None:
                enc = _compile_encoder(cls)
                _ENCODERS[cls] = enc
                metrics.wire_codec_compiles.inc()
    return enc


def encode(obj: Any) -> Any:
    """Encode a model value to JSON-compatible data (compiled fast path)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        cls = type(obj)
        enc = _ENCODERS.get(cls)
        if enc is None:
            enc = _encoder_for(cls)
        else:
            metrics.wire_codec_cache_hits.inc()
        return enc(obj)
    return _encode_value(obj)


# ---------------------------------------------------------------------------
# Compiled decoder
# ---------------------------------------------------------------------------


def _identity(v: Any) -> Any:
    return v


def _dc_field_decoder(declared: type) -> Callable[[Any], Any]:
    def dec(v: Any, _cls=declared) -> Any:
        if v is None:
            return None
        if not isinstance(v, dict):
            return v
        d = _DECODERS.get(_cls)
        if d is None:
            d = _decoder_for(_cls)
        return d(v)

    return dec


def _value_decoder(hint: Any) -> Callable[[Any], Any]:
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        # Optional[X] and small unions: decode to the first non-None arm
        # that is a structured type; primitives pass through.
        arms = [a for a in typing.get_args(hint) if a is not type(None)]
        if not arms:
            return _identity
        inner = _value_decoder(arms[0])
        return lambda v: None if v is None else inner(v)
    if origin in (list, tuple):
        args = typing.get_args(hint)
        inner = _value_decoder(args[0] if args else Any)
        return lambda v: None if v is None else [inner(x) for x in v]
    if origin is dict:
        args = typing.get_args(hint)
        inner = _value_decoder(args[1] if len(args) == 2 else Any)
        return (
            lambda v: None if v is None else {k: inner(x) for k, x in v.items()}
        )
    if isinstance(hint, type):
        if dataclasses.is_dataclass(hint):
            return _dc_field_decoder(hint)
        if issubclass(hint, enum.Enum):
            return lambda v, _e=hint: None if v is None else _e(v)
        if hint is float:
            return lambda v: float(v) if isinstance(v, int) else v
    return _identity


def _compile_decoder(cls: type) -> Callable[[Dict[str, Any]], Any]:
    hints = _hints(cls)
    steps = tuple(
        (f.name, _value_decoder(hints.get(f.name, Any)))
        for f in dataclasses.fields(cls)
    )

    def dec(data: Dict[str, Any], _steps=steps, _cls=cls) -> Any:
        kwargs = {}
        for name, fd in _steps:
            if name in data:
                kwargs[name] = fd(data[name])
        return _cls(**kwargs)

    return dec


def _decoder_for(cls: type) -> Callable[[Dict[str, Any]], Any]:
    dec = _DECODERS.get(cls)
    if dec is None:
        with _codec_lock:
            dec = _DECODERS.get(cls)
            if dec is None:
                dec = _compile_decoder(cls)
                _DECODERS[cls] = dec
                metrics.wire_codec_compiles.inc()
    return dec


def decode(data: Dict[str, Any], cls: Optional[type] = None) -> Any:
    """Decode a wire dict back into a model object.

    `cls` overrides the kind lookup (for nested calls); top-level callers
    normally rely on the `"kind"` discriminator.
    """
    if cls is None:
        kind = data.get("kind")
        cls = KIND_REGISTRY.get(kind or "")
        if cls is None:
            raise ValueError(f"unknown wire kind {kind!r}")
    dec = _DECODERS.get(cls)
    if dec is None:
        dec = _decoder_for(cls)
    else:
        metrics.wire_codec_cache_hits.inc()
    return dec(data)


# ---------------------------------------------------------------------------
# Reflection reference codec (the executable spec; NOT the hot path)
# ---------------------------------------------------------------------------


def reflect_encode(obj: Any) -> Any:
    """Original reflection codec: recursive, value-driven, one hint walk per
    field per call. Kept as the reference the compiled codec is
    property-tested against — any divergence is a compiled-codec bug."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {
            f.name: reflect_encode(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
        kind = getattr(type(obj), "KIND", None)
        if kind in KIND_REGISTRY:
            out["kind"] = kind
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [reflect_encode(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): reflect_encode(v) for k, v in obj.items()}
    return obj  # str/int/float/bool/None


def reflect_decode(data: Dict[str, Any], cls: Optional[type] = None) -> Any:
    if cls is None:
        kind = data.get("kind")
        cls = KIND_REGISTRY.get(kind or "")
        if cls is None:
            raise ValueError(f"unknown wire kind {kind!r}")
    return _reflect_decode_dataclass(data, cls)


def _reflect_decode_dataclass(data: Dict[str, Any], cls: type) -> Any:
    hints = _hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _reflect_decode_value(data[f.name], hints.get(f.name, Any))
    return cls(**kwargs)


def _reflect_decode_value(value: Any, hint: Any) -> Any:
    if value is None:
        return None
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        for arm in typing.get_args(hint):
            if arm is type(None):
                continue
            return _reflect_decode_value(value, arm)
        return value
    if origin in (list, tuple):
        args = typing.get_args(hint)
        elem = args[0] if args else Any
        return [_reflect_decode_value(v, elem) for v in value]
    if origin is dict:
        args = typing.get_args(hint)
        val_t = args[1] if len(args) == 2 else Any
        return {k: _reflect_decode_value(v, val_t) for k, v in value.items()}
    if isinstance(hint, type):
        if dataclasses.is_dataclass(hint):
            return _reflect_decode_dataclass(value, hint)
        if issubclass(hint, enum.Enum):
            return hint(value)
        if hint is float and isinstance(value, int):
            return float(value)
    return value


# ---------------------------------------------------------------------------
# Wire protocol v2 batch framing (shared client/server vocabulary)
# ---------------------------------------------------------------------------

# POST /batch envelopes: a sequence of length-prefixed sub-requests in one
# HTTP body, one wire round trip for N ops. Framing (request and response
# symmetric): one header line of JSON, then per op a JSON control line
# followed by exactly `l` raw body bytes. The sub-bodies are the compiled
# codec's output verbatim — the envelope never re-encodes.
BATCH_CONTENT_TYPE = "application/x-wire-batch"
BATCH_VERSION = 2


# ---------------------------------------------------------------------------
# Field-selector projections (wire protocol v2)
# ---------------------------------------------------------------------------


def parse_field_paths(fields: str) -> tuple:
    """Normalize a `fields=` selector string ("metadata,status.phase") into a
    sorted tuple of dotted paths — THE canonical form both the server's
    projected-body cache key and the projection itself use, so two spellings
    of the same selector share cache entries."""
    return tuple(sorted({p.strip() for p in fields.split(",") if p.strip()}))


def project_encoded(data: Dict[str, Any], paths: tuple) -> Dict[str, Any]:
    """Prune an already-encoded wire dict down to the requested dotted paths
    (plus the `kind` discriminator, which decode() needs). Runs on the
    compiled codec's OUTPUT, so projection never re-walks the dataclass —
    and a projected body decodes through the same kind registry: absent
    fields take their dataclass defaults, which is exactly the contract a
    lister that only reads metadata + status.phase relies on."""
    out: Dict[str, Any] = {}
    if "kind" in data:
        out["kind"] = data["kind"]
    for path in paths:
        src: Any = data
        dst = out
        segs = path.split(".")
        for i, seg in enumerate(segs):
            if not isinstance(src, dict) or seg not in src:
                break
            if i == len(segs) - 1:
                dst[seg] = src[seg]
            else:
                src = src[seg]
                dst = dst.setdefault(seg, {})
    return out


# ---------------------------------------------------------------------------
# Watch events
# ---------------------------------------------------------------------------


def encode_watch_event(ev) -> Dict[str, Any]:
    return {
        "type": ev.type,
        "kind": ev.kind,
        "status_only": ev.status_only,
        # The resume watermark (apiserver.WatchEvent.seq): clients track the
        # max seq observed per kind and present it on resubscribe so the
        # server can replay only the delta. Old payloads without it decode
        # to 0 (= not resumable past this event).
        "seq": getattr(ev, "seq", 0),
        "object": encode(ev.obj),
    }


_event_bytes_lock = TrackedLock("wire.event_bytes")


def encode_watch_event_bytes(ev) -> bytes:
    """JSON bytes of one watch event, serialized EXACTLY ONCE per event.

    The APIServer pushes one shared WatchEvent instance to every watcher
    (apiserver._notify), and the carried object is immutable by the informer
    contract — so the first wire drain to reach an event encodes it and
    caches the bytes on the event; every other session's drain reuses them.
    Before this, each of N watch sessions re-encoded every event on every
    poll: N-1 wasted serializations per event, pure host CPU on the
    1k-job-burst hot path. The double-checked lock keeps the miss counter
    honest (exactly one serialization even when two drains race)."""
    cached = ev.__dict__.get("_wire_bytes")
    if cached is not None:
        metrics.wire_event_cache_hits.inc()
        return cached
    with _event_bytes_lock:
        cached = ev.__dict__.get("_wire_bytes")
        if cached is not None:
            metrics.wire_event_cache_hits.inc()
            return cached
        body = json.dumps(
            encode_watch_event(ev), separators=(",", ":")
        ).encode()
        ev._wire_bytes = body
        metrics.wire_event_encodes.inc()
        return body


def decode_watch_event(d: Dict[str, Any]):
    from training_operator_tpu.cluster.apiserver import WatchEvent

    return WatchEvent(
        type=d["type"],
        kind=d["kind"],
        obj=decode(d["object"]),
        status_only=bool(d.get("status_only", False)),
        seq=int(d.get("seq", 0)),
    )
