"""Fault injection for the zero-hardware substrate.

The reference has no fault-injection tooling (SURVEY §5: failures are
simulated in tests by hand-setting pod phases); its recovery machinery —
exit-code triage, backoff limits, restart policies, gang re-admission —
is therefore only ever exercised one hand-written failure at a time. This
ChaosMonkey drives the same machinery under sustained random failure:
deterministic (seeded), budgeted, and virtual-clock friendly, so a test
can assert "every job converges despite N random kills" and replay the
exact kill sequence on failure.

Kills go through SimKubelet.complete_pod with a configurable exit code —
the same path a real container death takes — so pod restart policy,
engine triage (retryable >= 128 vs permanent), backoff counting, and
expectations all see an ordinary failure, not a test backdoor.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from training_operator_tpu.cluster.objects import PodPhase
from training_operator_tpu.cluster.runtime import Cluster, SimKubelet


class ChaosMonkey:
    """Kills a random running pod every `interval` until `budget` is spent.

    `selector` (label dict) and `namespace` scope the blast radius;
    `exit_code` defaults to 137 (SIGKILL — retryable under the reference's
    >= 128 rule, train_util.go:14). `kills` records (time, pod name) for
    assertions and replay."""

    def __init__(
        self,
        cluster: Cluster,
        kubelet: SimKubelet,
        seed: int = 0,
        interval: float = 5.0,
        budget: int = 10,
        exit_code: int = 137,
        selector: Optional[Dict[str, str]] = None,
        namespace: Optional[str] = None,
    ):
        self.cluster = cluster
        self.kubelet = kubelet
        self.rng = random.Random(seed)
        self.interval = interval
        self.budget = budget
        self.exit_code = exit_code
        self.selector = selector
        self.namespace = namespace
        self.kills: List[Tuple[float, str]] = []
        self._armed = True
        self._schedule_next()

    def stop(self) -> None:
        """Spend the remaining budget; in-flight timers become no-ops."""
        self._armed = False

    # ------------------------------------------------------------------

    def _schedule_next(self) -> None:
        if self._armed and len(self.kills) < self.budget:
            self.cluster.schedule_after(self.interval, self._strike)

    def _strike(self) -> None:
        if not self._armed or len(self.kills) >= self.budget:
            return
        victims = sorted(
            (
                p
                for p in self.cluster.api.list(
                    "Pod", self.namespace, self.selector
                )
                if p.status.phase == PodPhase.RUNNING
            ),
            key=lambda p: (p.namespace, p.name),
        )
        if victims:
            pod = self.rng.choice(victims)
            now = self.cluster.clock.now()
            if self.kubelet.complete_pod(
                pod.namespace, pod.name, exit_code=self.exit_code,
                log=f"chaos: killed at t={now:.1f}",
            ):
                self.kills.append((now, pod.name))
        self._schedule_next()
