"""Fault injection for the zero-hardware substrate.

The reference has no fault-injection tooling (SURVEY §5: failures are
simulated in tests by hand-setting pod phases); its recovery machinery —
exit-code triage, backoff limits, restart policies, gang re-admission —
is therefore only ever exercised one hand-written failure at a time. This
ChaosMonkey drives the same machinery under sustained random failure:
deterministic (seeded), budgeted, and virtual-clock friendly, so a test
can assert "every job converges despite N random kills" and replay the
exact kill sequence on failure.

Kills go through SimKubelet.complete_pod with a configurable exit code —
the same path a real container death takes — so pod restart policy,
engine triage (retryable >= 128 vs permanent), backoff counting, and
expectations all see an ordinary failure, not a test backdoor.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from training_operator_tpu.cluster.apiserver import ConflictError
from training_operator_tpu.cluster.objects import PodPhase
from training_operator_tpu.cluster.runtime import Cluster, SimKubelet
from training_operator_tpu.utils.locks import TrackedLock


class ChaosMonkey:
    """Kills a random running pod every `interval` until `budget` is spent.

    `selector` (label dict) and `namespace` scope the blast radius;
    `exit_code` defaults to 137 (SIGKILL — retryable under the reference's
    >= 128 rule, train_util.go:14). `kills` records (time, pod name) for
    assertions and replay."""

    def __init__(
        self,
        cluster: Cluster,
        kubelet: SimKubelet,
        seed: int = 0,
        interval: float = 5.0,
        budget: int = 10,
        exit_code: int = 137,
        selector: Optional[Dict[str, str]] = None,
        namespace: Optional[str] = None,
    ):
        self.cluster = cluster
        self.kubelet = kubelet
        self.rng = random.Random(seed)
        self.interval = interval
        self.budget = budget
        self.exit_code = exit_code
        self.selector = selector
        self.namespace = namespace
        self.kills: List[Tuple[float, str]] = []
        # Consecutive strikes that found no RUNNING victim. Without this, a
        # monkey whose jobs all finished keeps generating virtual-clock
        # events forever and run_until loops only end by timeout.
        self.empty_strikes = 0
        self.max_empty_strikes = 3
        self._armed = True
        self._schedule_next()

    def stop(self) -> None:
        """Spend the remaining budget; in-flight timers become no-ops."""
        self._armed = False

    # ------------------------------------------------------------------

    def _schedule_next(self) -> None:
        if self._armed and len(self.kills) < self.budget:
            self.cluster.schedule_after(self.interval, self._strike)

    def strike_once(self) -> Optional[str]:
        """One kill attempt NOW: pick a seeded random RUNNING victim and
        kill it through the kubelet; returns the victim pod name (None if
        nothing was killable). Public so an external schedule — the soak
        orchestrator interleaving every tier on one virtual clock — can
        drive strikes without owning this monkey's self-arming timer; the
        budget/empty-strike bookkeeping stays in the timer path."""
        return self._strike_once(
            self.cluster.api.list("Pod", self.namespace, self.selector)
        )

    def _strike_once(self, pods) -> Optional[str]:
        victims = sorted(
            (p for p in pods if p.status.phase == PodPhase.RUNNING),
            key=lambda p: (p.namespace, p.name),
        )
        if victims:
            pod = self.rng.choice(victims)
            now = self.cluster.clock.now()
            if self.kubelet.complete_pod(
                pod.namespace, pod.name, exit_code=self.exit_code,
                log=f"chaos: killed at t={now:.1f}",
            ):
                self.kills.append((now, pod.name))
                return pod.name
        return None

    def _strike(self) -> None:
        if not self._armed or len(self.kills) >= self.budget:
            return
        pods = self.cluster.api.list("Pod", self.namespace, self.selector)
        if self._strike_once(pods) is not None:
            self.empty_strikes = 0
        elif any(
            not p.is_terminal() and p.status.phase != PodPhase.RUNNING
            for p in pods
        ):
            # Matching pods exist but none are RUNNING yet (scheduling /
            # backoff delay): keep the monkey armed — disarming here would
            # silently strip chaos from a workload that is merely slow to
            # start, and tests relying on kills would pass vacuously.
            pass
        else:
            self.empty_strikes += 1
        if self.empty_strikes >= self.max_empty_strikes:
            self._armed = False  # nothing left to kill: disarm, stop ticking
            return
        self._schedule_next()


class NodeChaos:
    """Node-tier fault injection — the fourth chaos tier, next to the pod
    tier (ChaosMonkey), the store tier (APIChaos), and the wire tier
    (WireChaos). Kills are HOST deaths, not pod exits: the node's heartbeat
    goes silent via `SimKubelet.kill_node`, its pods freeze in their last
    written phase, and everything downstream — NotReady detection, the
    unreachable taint, eviction, gang re-placement — must be EARNED by the
    node lifecycle machinery, exactly as a real dead TPU host would demand.

    Three injection shapes, all virtual-clock friendly and logged for
    replay (`self.log` records (time, action, target); `self.kills` mirrors
    ChaosMonkey's (time, node) kill schedule):

      kill_node/recover_node     one host down (and optionally back)
      kill_slice                 a whole TPU slice at once — the correlated
                                 failure domain ICI-mesh placement creates
      maintenance_window         planned cordon+drain at `start`, uncordon
                                 after `duration` (the graceful twin)

    Random mode (budget > 0): every `interval` a seeded strike kills one
    node currently hosting a RUNNING pod; `recover_after` brings it back,
    modelling reboot-class outages. Identical seeds replay identical
    schedules."""

    def __init__(
        self,
        cluster: Cluster,
        kubelet: SimKubelet,
        seed: int = 0,
        interval: float = 60.0,
        budget: int = 0,
        recover_after: Optional[float] = None,
    ):
        self.cluster = cluster
        self.kubelet = kubelet
        self.rng = random.Random(seed)
        self.interval = interval
        self.budget = budget
        self.recover_after = recover_after
        self.kills: List[Tuple[float, str]] = []
        self.log: List[Tuple[float, str, str]] = []
        self.empty_strikes = 0
        self.max_empty_strikes = 3
        self._armed = True
        if budget > 0:
            self.cluster.schedule_after(self.interval, self._strike)

    def stop(self) -> None:
        self._armed = False

    # -- explicit injections -------------------------------------------

    def _record(self, action: str, target: str) -> None:
        self.log.append((self.cluster.clock.now(), action, target))

    def kill_node(self, name: str) -> None:
        self.kubelet.kill_node(name)
        now = self.cluster.clock.now()
        self.kills.append((now, name))
        self._record("kill", name)

    def recover_node(self, name: str) -> None:
        self.kubelet.recover_node(name)
        self._record("recover", name)

    def kill_slice(self, slice_id: str) -> List[str]:
        """Correlated failure: every host of one TPU slice dies at once."""
        members = [
            n.name
            for n in self.cluster.api.list_refs("Node")
            if n.accelerator.kind == "tpu" and n.accelerator.tpu_slice == slice_id
        ]
        for name in sorted(members):
            self.kill_node(name)
        self._record("kill_slice", slice_id)
        return sorted(members)

    def schedule_kill(self, name: str, at: float) -> None:
        self.cluster.schedule_at(at, lambda: self._armed and self.kill_node(name))

    def schedule_recover(self, name: str, at: float) -> None:
        self.cluster.schedule_at(at, lambda: self._armed and self.recover_node(name))

    def maintenance_window(self, name: str, start: float, duration: float) -> None:
        """Planned outage: cordon+drain at `start` (pods rescheduled
        gracefully, gangs re-solved), uncordon at `start + duration`."""
        from training_operator_tpu.controllers.nodelifecycle import (
            drain_node,
            uncordon_node,
        )

        def begin():
            if not self._armed:
                return
            drain_node(self.cluster.api, name, now=self.cluster.clock.now())
            self._record("maintenance_begin", name)

        def end():
            if not self._armed:
                return
            uncordon_node(self.cluster.api, name, now=self.cluster.clock.now())
            self._record("maintenance_end", name)

        self.cluster.schedule_at(start, begin)
        self.cluster.schedule_at(start + duration, end)

    # -- random strikes ------------------------------------------------

    def strike_once(self) -> Optional[str]:
        """One node kill NOW: a seeded random host currently running a pod
        goes dark (recover_after schedules its reboot); returns the victim
        node name (None when no busy live node exists). Public for external
        schedules — see ChaosMonkey.strike_once."""
        return self._strike_once(self.cluster.api.list("Pod"))

    def _strike_once(self, pods) -> Optional[str]:
        busy = sorted({
            p.node_name
            for p in pods
            if p.node_name
            and p.status.phase == PodPhase.RUNNING
            and self.kubelet.node_alive(p.node_name)
        })
        if not busy:
            return None
        victim = self.rng.choice(busy)
        self.kill_node(victim)
        if self.recover_after is not None:
            self.schedule_recover(
                victim, self.cluster.clock.now() + self.recover_after
            )
        return victim

    def _strike(self) -> None:
        if not self._armed or len(self.kills) >= self.budget:
            return
        pods = self.cluster.api.list("Pod")
        if self._strike_once(pods) is not None:
            self.empty_strikes = 0
        elif any(not p.is_terminal() for p in pods):
            # Pods exist but none RUNNING yet (scheduling/recovery lag):
            # stay armed, like ChaosMonkey — disarming would quietly strip
            # chaos from a slow-starting workload.
            pass
        else:
            self.empty_strikes += 1
        if self.empty_strikes >= self.max_empty_strikes:
            self._armed = False
            return
        if len(self.kills) < self.budget:
            self.cluster.schedule_after(self.interval, self._strike)


class HostChaos:
    """Control-plane HOST death — the fifth chaos tier. ChaosMonkey kills
    pods, NodeChaos kills worker hosts, APIChaos corrupts store semantics,
    WireChaos corrupts the transport; this tier kills the process that IS
    the control plane, mid-burst, so everything PR 9 built — WAL-shipped
    warm standby, lease-expiry promotion, epoch-chained watch resume,
    client address failover — must be EARNED, not assumed.

    Two kill shapes, matching the two ways tests run a host:

      kill_inprocess(...)   SIGKILL semantics for an in-process host stack:
                            the step loop stops mid-stride (stop event),
                            the HTTP listener and its sessions die
                            (server.close), and the durable store's fd is
                            ABANDONED — never flushed or compacted again
                            (HostStore.abandon), exactly the state kill -9
                            leaves on disk. Components are keyword-optional
                            so partial stacks (no store) inject the same.
      kill_process(proc)    SIGKILL a real host OS process (subprocess
                            .Popen) and reap it — the cross-process twin.

    `log` records (wall time, action, target) and `kills` mirrors the
    NodeChaos (time, target) schedule for replay/assertions."""

    def __init__(self):
        import time as _time

        self._now = _time.time
        self.kills: List[Tuple[float, str]] = []
        self.log: List[Tuple[float, str, str]] = []

    def _record(self, action: str, target: str) -> float:
        now = self._now()
        self.log.append((now, action, target))
        return now

    def kill_inprocess(self, name: str = "primary", server=None, store=None,
                       stop=None, threads=()) -> float:
        """Abruptly kill an in-process host stack; returns the kill wall
        time (MTTR measurements start here). Order matters: the step loop
        is halted FIRST so no timer fires into a half-dead stack, then the
        wire goes dark, then the store is abandoned."""
        if stop is not None:
            stop.set()
        for t in threads:
            # Step threads are daemons; a bounded join keeps the kill
            # "instant" from the cluster's perspective without leaking an
            # actively stepping loop into the post-mortem assertions.
            t.join(timeout=5.0)
        if server is not None:
            # kill() severs established keep-alive connections too (a
            # graceful close would let the standby's WAL long-poll keep
            # being served by a "dead" host); plain close() for servers
            # without the abrupt arm.
            getattr(server, "kill", server.close)()
        if store is not None:
            store.abandon()
        now = self._record("kill_inprocess", name)
        self.kills.append((now, name))
        return now

    def kill_process(self, proc, name: str = "primary") -> float:
        """SIGKILL a host OS process and reap it; returns the kill time."""
        import signal as _signal

        proc.send_signal(_signal.SIGKILL)
        proc.wait()
        now = self._record("kill_process", name)
        self.kills.append((now, name))
        return now

    def promote(self, standby_controller, reason: str = "chaos promotion") -> None:
        """Request promotion on an in-process StandbyController (the
        explicit-verb arm; lease-expiry auto-promotion needs no help).
        The owner's loop completes it via maybe_complete_promotion."""
        standby_controller.request_promotion(reason)
        self._record("promote", standby_controller.identity)


class APIChaos:
    """Control-plane fault injection against one APIServer.

    The reference's subtlest machinery exists to survive exactly these
    faults: the expectations cache absorbs the create->informer-echo gap
    (expectation/expectation.go:29-40), adoption re-checks and versioned
    writes absorb conflicts (control/controller_ref_manager.go:380), and
    controller-runtime's SyncPeriod resync heals missed watch events. This
    injector produces those faults ON DEMAND, seeded and budget-free:

      conflict_rate  fraction of version-checked update() calls that raise
                     ConflictError even when the version matches (the
                     optimistic-concurrency writer must retry via its
                     backoff/requeue path). Unversioned writes (kubelet
                     status flips) are never targeted — real kubelets
                     don't do optimistic concurrency here.
      drop_rate      fraction of watch events NOT delivered to the victim
                     watcher (flaky informer connection). Healed by the
                     manager's periodic resync.
      dup_rate       fraction of watch events delivered TWICE to the victim
                     (reconnect replay) — reconciles must be idempotent and
                     expectations must not double-count.
      stall          (start, duration): during the window, the victim's
                     events are buffered and delivered only after it ends
                     (informer stall / network partition).

    `victims` scopes drop/dup/stall to specific watch queues (normally the
    operator manager's): faulting EVERY component's watch would model a
    substrate with no reliable delivery anywhere, which even Kubernetes
    does not claim to be.

    `stop()` restores the pristine APIServer methods.
    """

    def __init__(
        self,
        cluster: Cluster,
        seed: int = 0,
        conflict_rate: float = 0.0,
        drop_rate: float = 0.0,
        dup_rate: float = 0.0,
        stall: Optional[Tuple[float, float]] = None,
        victims: Optional[List[object]] = None,
    ):
        self.cluster = cluster
        self.api = cluster.api
        self.rng = random.Random(seed)
        self.conflict_rate = conflict_rate
        self.drop_rate = drop_rate
        self.dup_rate = dup_rate
        self.stall = stall
        self.victims = list(victims or [])
        self.injected_conflicts = 0
        self.dropped_events = 0
        self.duplicated_events = 0
        self.stalled_events = 0
        self._stall_buffer: List[Tuple[object, object]] = []
        self._orig_update = self.api.update
        self._orig_notify = self.api._notify
        self.api.update = self._update
        self.api._notify = self._notify
        if stall is not None:
            # Flush timer: buffered events land right after the window ends.
            cluster.schedule_at(stall[0] + stall[1], self._flush_stall)

    def stop(self) -> None:
        self.api.update = self._orig_update
        self.api._notify = self._orig_notify
        self._flush_stall()

    # ------------------------------------------------------------------

    def _update(self, obj, check_version: bool = True, status_only: bool = False):
        if check_version and self.conflict_rate and self.rng.random() < self.conflict_rate:
            self.injected_conflicts += 1
            key = (obj.KIND, getattr(obj.metadata, "namespace", ""), obj.metadata.name)
            raise ConflictError(f"chaos: injected conflict on {key}")
        return self._orig_update(obj, check_version=check_version, status_only=status_only)

    def _in_stall(self) -> bool:
        if self.stall is None:
            return False
        start, dur = self.stall
        return start <= self.cluster.clock.now() < start + dur

    def _flush_stall(self) -> None:
        buffered, self._stall_buffer = self._stall_buffer, []
        for victim, ev in buffered:
            victim.push(ev)

    def _notify(self, ev_type: str, obj, status_only: bool = False) -> None:
        from training_operator_tpu.cluster.apiserver import WatchEvent

        if not self.victims:
            self._orig_notify(ev_type, obj, status_only=status_only)
            return
        # Deliver per-watcher so faults hit only the victims; everyone else
        # observes perfectly ordered, exactly-once delivery.
        ev = WatchEvent(ev_type, obj.KIND, obj, status_only=status_only)
        for w in list(self.api._watchers):
            if w not in self.victims:
                w.push(ev)
                continue
            if self._in_stall():
                self.stalled_events += 1
                self._stall_buffer.append((w, ev))
                continue
            r = self.rng.random()
            if r < self.drop_rate:
                self.dropped_events += 1
                continue
            w.push(ev)
            if r < self.drop_rate + self.dup_rate:
                self.duplicated_events += 1
                w.push(ev)


class GangPause:
    """Pause a component's ticker for a window (scheduler outage): ticks
    inside [start, start+duration) are swallowed. Models the gang scheduler
    or default scheduler being down while the rest of the control plane
    keeps moving — pods must queue, not error."""

    def __init__(self, cluster: Cluster, ticker, start: float, duration: float):
        self.cluster = cluster
        self.ticker = ticker
        self.start = start
        self.duration = duration
        cluster.remove_ticker(ticker)
        cluster.add_ticker(self._gated)

    def _gated(self) -> None:
        now = self.cluster.clock.now()
        if self.start <= now < self.start + self.duration:
            return
        self.ticker()

    def stop(self) -> None:
        self.cluster.remove_ticker(self._gated)
        self.cluster.add_ticker(self.ticker)


class WireChaos:
    """Fault injection at the HTTP wire boundary (`ApiHTTPServer`).

    `APIChaos` above attacks the STORE's semantics (conflicts, dropped
    watch events); this tier attacks the TRANSPORT the way real networks
    do, exercising the client-side arms none of the in-process chaos can
    reach: `RemoteAPIServer`'s 5xx mapping (`ApiServerError`), the
    connection-reset path (`ApiUnavailableError`), `RemoteRuntime.
    run_forever`'s retry/backoff arm, and `RemoteWatchQueue.drain`'s
    resubscribe-after-reap healing (httpapi.py). Seeded; sampling is
    serialized under a lock so a seed reproduces the same DECISION
    sequence (request arrival order stays OS-scheduled, as in any real
    network test).

      error_rate   probability a request is answered 500 before dispatch
      reset_rate   probability the connection is closed with no response
                   at all (TCP reset as the client sees it)
      reap_rate    probability ALL server-side watch sessions are reaped
                   before serving (session loss under memory pressure /
                   host failover; clients must resubscribe + resync)

    Probes (/healthz, /readyz) are exempt, like kubelet probes riding a
    management port. `injected` counts per-kind injections so tests can
    assert the storm actually happened.
    """

    def __init__(
        self,
        seed: int = 0,
        error_rate: float = 0.0,
        reset_rate: float = 0.0,
        reap_rate: float = 0.0,
    ):
        import threading

        self.rng = random.Random(seed)
        self.error_rate = error_rate
        self.reset_rate = reset_rate
        self.reap_rate = reap_rate
        self.injected: Dict[str, int] = {"error": 0, "reset": 0, "reap": 0}
        self._lock = TrackedLock("wire_chaos")

    @classmethod
    def from_spec(cls, spec: str) -> "WireChaos":
        """Parse "seed=3,error=0.1,reset=0.05,reap=0.02" (env/CLI form)."""
        kwargs: Dict[str, float] = {}
        for pair in spec.split(","):
            if not pair.strip():
                continue
            key, _, value = pair.partition("=")
            key = key.strip()
            name = {"seed": "seed", "error": "error_rate",
                    "reset": "reset_rate", "reap": "reap_rate"}.get(key)
            if name is None:
                raise ValueError(f"unknown wire-chaos key {key!r} in {spec!r}")
            kwargs[name] = int(value) if name == "seed" else float(value)
        return cls(**kwargs)

    def sample(self) -> Optional[str]:
        """One decision per request: "error" | "reset" | "reap" | None."""
        with self._lock:
            r = self.rng.random()
            if r < self.error_rate:
                self.injected["error"] += 1
                return "error"
            r -= self.error_rate
            if r < self.reset_rate:
                self.injected["reset"] += 1
                return "reset"
            r -= self.reset_rate
            if r < self.reap_rate:
                self.injected["reap"] += 1
                return "reap"
            return None
