"""In-process API server: object store + watch streams + optimistic concurrency.

This is the substrate the reconcile engine writes to, standing in for the
Kubernetes API server. Two properties matter and are reproduced faithfully:

1. **Asynchronous watch echo.** Writes return immediately, but watch events are
   *queued* and only observed when the consumer drains its informer queue.
   This is exactly the window the reference's expectations cache exists for
   (expectation/expectation.go:29-40): between `CreatePod` returning and the
   informer seeing the new pod, a naive reconcile would create duplicates.

2. **Optimistic concurrency.** Every write bumps `resourceVersion`; an update
   carrying a stale version conflicts (like k8s), which the engine's status
   writer must retry (reference UpdateJobStatusInApiServer path).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Tuple

from training_operator_tpu.cluster.objects import Event


class ConflictError(Exception):
    """Stale resourceVersion on update."""


class AlreadyExistsError(Exception):
    pass


class NotFoundError(Exception):
    pass


@dataclass
class WatchEvent:
    type: str  # Added | Modified | Deleted
    kind: str
    obj: Any
    # True for writes that only touched .status (controllers' own writes);
    # managers skip re-enqueueing these to avoid self-echo reconcile storms
    # (the role GenerationChangedPredicate plays in controller-runtime).
    status_only: bool = False


class WatchQueue:
    """A subscriber's pending-event queue (an informer's delta FIFO)."""

    def __init__(self, kinds: Optional[Iterable[str]] = None):
        self.kinds = set(kinds) if kinds else None
        self._q: Deque[WatchEvent] = deque()

    def push(self, ev: WatchEvent) -> None:
        if self.kinds is None or ev.kind in self.kinds:
            self._q.append(ev)

    def drain(self) -> List[WatchEvent]:
        out = list(self._q)
        self._q.clear()
        return out

    def __len__(self) -> int:
        return len(self._q)


class APIServer:
    """Typed object store keyed by (kind, namespace, name)."""

    def __init__(self) -> None:
        self._objects: Dict[Tuple[str, str, str], Any] = {}
        # Per-kind index so list(kind) doesn't scan the whole store — at
        # 1k-job-burst scale the reconcilers list pods thousands of times.
        self._by_kind: Dict[str, Dict[Tuple[str, str], Any]] = {}
        self._rv_value = 0
        self._watchers: List[WatchQueue] = []
        self._events: List[Event] = []
        self._lock = threading.RLock()
        # Admission hooks: kind -> [callable(obj) raising on rejection]
        self._admission: Dict[str, List[Callable[[Any], None]]] = {}

    # -- admission ---------------------------------------------------------

    def register_admission(self, kind: str, fn: Callable[[Any], None]) -> None:
        self._admission.setdefault(kind, []).append(fn)

    # -- watch -------------------------------------------------------------

    def watch(self, kinds: Optional[Iterable[str]] = None) -> WatchQueue:
        wq = WatchQueue(kinds)
        with self._lock:
            self._watchers.append(wq)
        return wq

    def _next_rv(self) -> int:
        self._rv_value += 1
        return self._rv_value

    def version(self) -> int:
        """Global write counter — lets the cluster loop detect quiescence."""
        with self._lock:
            return self._rv_value

    def _notify(self, ev_type: str, obj: Any, status_only: bool = False) -> None:
        ev = WatchEvent(ev_type, obj.KIND, obj, status_only=status_only)
        for w in self._watchers:
            w.push(ev)

    # -- CRUD --------------------------------------------------------------

    @staticmethod
    def _key(obj: Any) -> Tuple[str, str, str]:
        ns = getattr(obj.metadata, "namespace", "") or ""
        return (obj.KIND, ns, obj.metadata.name)

    def create(self, obj: Any) -> Any:
        with self._lock:
            for fn in self._admission.get(obj.KIND, []):
                fn(obj)
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            obj.metadata.ensure_uid(obj.KIND)
            obj.metadata.resource_version = self._next_rv()
            self._objects[key] = obj
            self._by_kind.setdefault(key[0], {})[key[1:]] = obj
            self._notify("Added", obj)
            return obj

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            try:
                return self._objects[(kind, namespace or "", name)]
            except KeyError:
                raise NotFoundError(f"{kind} {namespace}/{name} not found") from None

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        with self._lock:
            return self._objects.get((kind, namespace or "", name))

    def update(self, obj: Any, check_version: bool = True, status_only: bool = False) -> Any:
        with self._lock:
            key = self._key(obj)
            current = self._objects.get(key)
            if current is None:
                raise NotFoundError(f"{key} not found")
            if check_version and current is not obj and (
                obj.metadata.resource_version != current.metadata.resource_version
            ):
                raise ConflictError(
                    f"{key}: stale resourceVersion {obj.metadata.resource_version} "
                    f"!= {current.metadata.resource_version}"
                )
            obj.metadata.resource_version = self._next_rv()
            self._objects[key] = obj
            self._by_kind.setdefault(key[0], {})[key[1:]] = obj
            self._notify("Modified", obj, status_only=status_only)
            return obj

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            key = (kind, namespace or "", name)
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{key} not found")
            self._by_kind.get(kind, {}).pop(key[1:], None)
            self._notify("Deleted", obj)
            return obj

    def try_delete(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        with self._lock:
            out = []
            for (ns, _), obj in self._by_kind.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                if label_selector:
                    labels = obj.metadata.labels
                    if not all(labels.get(lk) == lv for lk, lv in label_selector.items()):
                        continue
                out.append(obj)
            return out

    # -- events ------------------------------------------------------------

    def record_event(self, event: Event) -> None:
        with self._lock:
            self._events.append(event)

    def events(
        self, object_name: Optional[str] = None, reason: Optional[str] = None
    ) -> List[Event]:
        with self._lock:
            return [
                e
                for e in self._events
                if (object_name is None or e.object_name == object_name)
                and (reason is None or e.reason == reason)
            ]
