"""In-process API server: object store + watch streams + optimistic concurrency.

This is the substrate the reconcile engine writes to, standing in for the
Kubernetes API server. Three properties matter and are reproduced faithfully:

1. **Asynchronous watch echo.** Writes return immediately, but watch events are
   *queued* and only observed when the consumer drains its informer queue.
   This is exactly the window the reference's expectations cache exists for
   (expectation/expectation.go:29-40): between `CreatePod` returning and the
   informer seeing the new pod, a naive reconcile would create duplicates.

2. **Optimistic concurrency.** Every write bumps `resourceVersion`; an update
   carrying a stale version conflicts (like k8s), which the engine's status
   writer must retry (reference UpdateJobStatusInApiServer path).

3. **Copy-on-read.** get/list return deep copies and writes store copies, so
   in-place mutation of a read object never reaches the store without an
   update() — the class of stale-read/lost-update bug real k8s surfaces is
   surfaced here too instead of being structurally invisible. Watch events
   carry ONE shared copy per write (the informer contract: handlers may keep
   the object but must treat it as read-only or accept cross-watcher skew;
   the store itself can't be corrupted either way).

A per-(kind, label) inverted index backs label-selector lists, so the engine's
per-job pod/service lookups don't scan (and clone) the whole pod population.
"""

from __future__ import annotations

import copy as _copylib
import threading
import time as _time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Tuple

from training_operator_tpu.cluster.objects import Event
from training_operator_tpu.observe.timeline import TimelineStore
from training_operator_tpu.utils import metrics
from training_operator_tpu.utils.locks import TrackedCondition, TrackedRLock

# Default event-retention cap (see APIServer._event_cap). Sized to hold
# every event of a 1k-job burst several times over; long-lived hosts and
# soak runs may lower it via set_event_cap.
DEFAULT_EVENT_CAP = 16384


def _is_job_like(obj: Any) -> bool:
    """Objects the lifecycle tracer follows: v1 jobs (replica_specs) and v2
    TrainJobs — not pods/services/etc., whose churn would flood the ring."""
    return hasattr(obj, "replica_specs") or obj.KIND == "TrainJob"


class ConflictError(Exception):
    """Stale resourceVersion on update."""


class AlreadyExistsError(Exception):
    pass


class NotFoundError(Exception):
    pass


def graft_status_retry(try_get: Callable, update: Callable, obj: Any) -> None:
    """THE conflict arm for status writes, shared by the engine's
    synchronous retry and the wire coalescer's flush-boundary retry so the
    two can never diverge: re-get the current stored version, graft the
    writer's status AND its annotation changes (the recreate-restart
    budget rides an annotation — dropping its bump on a raced write would
    let a crash-looping job restart past its backoff limit forever), then
    write unconditionally (the controller's tally is the truth source).
    NotFoundError from either call means the object was deleted in the
    race window — nothing left to write; callers decide what that means."""
    fresh = try_get(
        obj.KIND, getattr(obj.metadata, "namespace", "") or "", obj.metadata.name
    )
    if fresh is None:
        return
    fresh.status = obj.status
    merged = dict(fresh.metadata.annotations)
    merged.update(obj.metadata.annotations)
    fresh.metadata.annotations = merged
    update(fresh, check_version=False, status_only=True)


@dataclass
class WatchEvent:
    type: str  # Added | Modified | Deleted
    kind: str
    obj: Any
    # True for writes that only touched .status (controllers' own writes);
    # managers skip re-enqueueing these to avoid self-echo reconcile storms
    # (the role GenerationChangedPredicate plays in controller-runtime).
    status_only: bool = False
    # Per-APIServer monotonic event sequence, assigned by _notify. This is
    # the wire watch layer's ResourceVersion watermark: a client that has
    # observed seq N has observed EVERY event up to N (deletes don't bump
    # the object rv counter, so the object rv alone can't order a stream
    # that includes Deleted events). 0 = synthesized event (client-side
    # relist), never a store notification.
    seq: int = 0


class WatchQueue:
    """A subscriber's pending-event queue (an informer's delta FIFO)."""

    def __init__(self, kinds: Optional[Iterable[str]] = None):
        self.kinds = set(kinds) if kinds else None
        self._q: Deque[WatchEvent] = deque()

    def push(self, ev: WatchEvent) -> None:
        if self.kinds is None or ev.kind in self.kinds:
            self._q.append(ev)

    def drain(self) -> List[WatchEvent]:
        out = list(self._q)
        self._q.clear()
        return out

    def __len__(self) -> int:
        return len(self._q)


class SharedInformer:
    """Cluster-wide read cache fed by one watch stream (controller-runtime's
    shared informer). Components read full state from here instead of listing
    (and cloning) the store on every tick; the cache holds the per-write
    event copies, so reads are O(1) and allocation-free.

    Contract: cached objects are the shared event copies — treat them as
    read-only unless you immediately persist the same change with update()
    (write-through). sync() applies queued events; `Cluster.step` calls it
    before tickers run, so caches lag the store by at most one tick — the
    same lag every real informer has.
    """

    def __init__(self, api: "APIServer"):
        self._watch = api.watch()
        self.caches: Dict[str, Dict[Tuple[str, str], Any]] = {}
        # Seed from the store (initial LIST, then WATCH).
        for kind in list(api._by_kind):
            for obj in api.list(kind):
                ns = getattr(obj.metadata, "namespace", "") or ""
                self.caches.setdefault(kind, {})[(ns, obj.metadata.name)] = obj

    def sync(self) -> None:
        for ev in self._watch.drain():
            ns = getattr(ev.obj.metadata, "namespace", "") or ""
            key = (ns, ev.obj.metadata.name)
            if ev.type == "Deleted":
                self.caches.get(ev.kind, {}).pop(key, None)
            else:
                self.caches.setdefault(ev.kind, {})[key] = ev.obj

    def list(self, kind: str) -> List[Any]:
        return list(self.caches.get(kind, {}).values())

    def get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        return self.caches.get(kind, {}).get((namespace or "", name))


class APIServer:
    """Typed object store keyed by (kind, namespace, name)."""

    def __init__(self) -> None:
        self._objects: Dict[Tuple[str, str, str], Any] = {}
        # Per-kind index so list(kind) doesn't scan the whole store — at
        # 1k-job-burst scale the reconcilers list pods thousands of times.
        self._by_kind: Dict[str, Dict[Tuple[str, str], Any]] = {}
        # Inverted label index: (kind, label_key, label_value) -> {(ns, name)}
        # so selector lists touch only matching objects.
        self._by_label: Dict[Tuple[str, str, str], set] = {}
        self._rv_value = 0
        # Watch-event sequence (see WatchEvent.seq): distinct from the rv
        # counter because deletes notify without bumping rv, and restored
        # objects notify at their restored rv.
        self._event_seq = 0
        self._watchers: List[WatchQueue] = []
        self._events: List[Event] = []
        # Event aggregation index (k8s parity): aggregation_key -> index in
        # _events, so identical repeats bump a count instead of appending.
        self._event_index: Dict[tuple, int] = {}
        # Per-object read index: object_name -> indices into _events, so
        # `events(object_name=...)` (the explain/attribution evidence read,
        # issued once per job) is O(own events), not a full-list scan.
        self._events_by_name: Dict[str, List[int]] = {}
        # Event retention bound (the k8s events-TTL analogue, count-shaped
        # for a virtual-clock store): the event list was the last unbounded
        # accumulator in the control plane — a week-long soak grows it
        # linearly with fleet life while everything else (timelines, resume
        # rings, WAL ring, pod logs) is ring-bounded. Past the cap the
        # OLDEST quarter is dropped (hysteresis: trimming exactly to cap
        # would rebuild the aggregation index on every append once full).
        # Aggregated repeats keep bumping retained records; a repeat of a
        # dropped record starts a fresh count, exactly like an expired k8s
        # Event recurring.
        self._event_cap = DEFAULT_EVENT_CAP
        self._lock = TrackedRLock("apiserver")
        # Signalled on every watch push; wait_and_drain blocks on it so a
        # cross-thread watch consumer (the HTTP long-poll handler) parks on
        # a condition instead of spinning. Shares the store lock: a waiter
        # holding the condition atomically releases the lock while blocked.
        self._watch_cond = TrackedCondition(self._lock, name="apiserver")
        # Durability sink (cluster/store.py HostStore): called inside the
        # lock after every mutation, so the journal order IS the write
        # order. None = volatile store (tests, standalone role).
        self._journal: Optional[Callable[..., None]] = None
        # Admission hooks: kind -> [callable(obj) raising on rejection]
        self._admission: Dict[str, List[Callable[[Any], None]]] = {}
        # Per-pod log buffers (the k8s pod-log subresource analogue): the
        # kubelet appends lifecycle + container stdout lines; readers tail
        # by cursor so `follow` streaming is O(new lines). Bounded per pod;
        # `base` keeps cursors stable across trimming. Logs die with the
        # pod object, like kubelet-held logs do.
        self._pod_logs: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._pod_log_max = 4096
        # Job-lifecycle timeline ring (observe/timeline.py): admission,
        # queue-wait, reconcile, gang-solve, bind, and condition-transition
        # spans land here, served at GET /timelines/{ns}/{name}. The owning
        # Cluster injects its clock so virtual-clock sims trace in sim time.
        self.timelines = TimelineStore()

    @staticmethod
    def _clone(obj: Any) -> Any:
        return _copylib.deepcopy(obj)

    def _index_labels(self, key: Tuple[str, str, str], obj: Any) -> None:
        for lk, lv in obj.metadata.labels.items():
            self._by_label.setdefault((key[0], lk, lv), set()).add(key[1:])

    def _unindex_labels(self, key: Tuple[str, str, str], obj: Any) -> None:
        for lk, lv in obj.metadata.labels.items():
            bucket = self._by_label.get((key[0], lk, lv))
            if bucket is not None:
                bucket.discard(key[1:])

    # -- durability --------------------------------------------------------

    def attach_journal(self, sink: Callable[..., None]) -> None:
        """Register the durability sink; see HostStore. Calls arrive inside
        the store lock as sink(op, *args) with op in put/del/event/log."""
        with self._lock:
            self._journal = sink

    def locked(self):
        """The store lock as a public context manager — for consumers that
        must compose several calls atomically (snapshot+journal rotation)
        without reaching into `_lock`."""
        return self._lock

    def snapshot_refs(self) -> Dict[str, Any]:
        """CHEAP capture of full state under the lock: object REFERENCES
        (safe — updates replace stored objects, never mutate them in
        place), a copy of the append-only event list, and copies of the
        pod-log line lists (those ARE mutated in place). The caller encodes
        OUTSIDE the lock — on a large store the wire-encode is the
        expensive part, and doing it under the lock would stall every
        concurrent API request (see HostStore.compact)."""
        with self._lock:
            return {
                "rv": self._rv_value,
                "objects": list(self._objects.values()),
                "events": list(self._events),
                "pod_logs": [
                    (ns, name, buf["base"], list(buf["lines"]))
                    for (ns, name), buf in self._pod_logs.items()
                ],
            }

    def snapshot_state(self) -> Dict[str, Any]:
        """Wire-encoded full state for a snapshot file (atomic capture,
        encode included — prefer snapshot_refs + encode_snapshot when the
        lock must stay cheap)."""
        return encode_snapshot(self.snapshot_refs())

    def restore(
        self,
        objects: List[Any],
        rv: int,
        events: Optional[List[Event]] = None,
        pod_logs: Optional[Dict[Tuple[str, str], Dict[str, Any]]] = None,
    ) -> None:
        """Load recovered state (HostStore.load_into). Bypasses admission
        and uid assignment — these objects already passed both in their
        first life — but announces each as an Added watch event so informers
        constructed before the restore converge. Advances the uid counter
        past every restored uid (advance_uid_floor) so a recreated name can
        never collide with a dead incarnation's uid (controllers key
        liveness on uid)."""
        with self._lock:
            for obj in objects:
                key = self._key(obj)
                stored = self._clone(obj)
                self._objects[key] = stored
                self._by_kind.setdefault(key[0], {})[key[1:]] = stored
                self._index_labels(key, stored)
                self._notify("Added", self._clone(stored))
            self._rv_value = max(self._rv_value, rv)
            for ev in events or []:
                # Through the aggregation path: journal replay delivers one
                # record per occurrence, and restored counts must match what
                # the dead incarnation's readers saw.
                self._merge_event_locked(ev)
            if pod_logs:
                for key2, buf in pod_logs.items():
                    self._pod_logs[key2] = {
                        "lines": list(buf["lines"]), "base": int(buf["base"])
                    }
            self.advance_uid_floor()

    def apply_replicated(self, rec: Dict[str, Any]) -> None:
        """Apply one shipped WAL record (the standby's ingest path): the
        same op vocabulary HostStore._apply replays from disk, but into the
        LIVE store — with watch notify (standby watch sessions and the
        resume ring observe replicated events), local write-ahead journal
        (a standby with its own state dir is durable in its own right), and
        the primary's resourceVersions preserved verbatim. Bypasses
        admission and optimistic concurrency: these writes already passed
        both on the primary, and the journal order being applied IS the
        primary's write order.

        Seq lockstep invariant: every put/del record advances _event_seq by
        EXACTLY one (put and del each notify once; a del of a key this
        store never saw — a gap that a complete stream cannot produce —
        still burns its seq), and event/log records never notify, mirroring
        record_event/append_pod_log on the primary. See set_event_seq."""
        from training_operator_tpu.cluster import wire

        op = rec.get("op")
        if op == "event":
            self.record_event(wire.decode(rec["event"], Event))
            return
        if op == "log":
            self.append_pod_log(
                rec.get("ns", ""), rec["name"], str(rec.get("line", "")),
                float(rec.get("ts", 0.0)),
            )
            return
        with self._lock:
            if op == "put":
                obj = wire.decode(rec["obj"])
                key = self._key(obj)
                status_only = bool(rec.get("so"))
                if self._journal is not None:  # write-ahead, see create()
                    self._journal("put", obj, status_only)
                prev = self._objects.get(key)
                if prev is not None:
                    self._unindex_labels(key, prev)
                self._objects[key] = obj
                self._by_kind.setdefault(key[0], {})[key[1:]] = obj
                self._index_labels(key, obj)
                self._rv_value = max(
                    self._rv_value, int(obj.metadata.resource_version or 0)
                )
                self._notify(
                    "Added" if prev is None else "Modified",
                    self._clone(obj), status_only=status_only,
                )
            elif op == "del":
                key = (rec["kind"], rec.get("ns", "") or "", rec["name"])
                if self._journal is not None:  # write-ahead, see create()
                    self._journal("del", key[0], key[1], key[2],
                                  int(rec.get("rv", 0)))
                obj = self._objects.pop(key, None)
                self._by_kind.get(key[0], {}).pop(key[1:], None)
                self._rv_value = max(self._rv_value, int(rec.get("rv", 0)))
                if obj is not None:
                    self._unindex_labels(key, obj)
                    if key[0] == "Pod":
                        self._pod_logs.pop(key[1:], None)
                    self._notify("Deleted", obj)
                else:  # pragma: no cover - complete streams can't get here
                    self._event_seq += 1  # burn the seq: lockstep holds

    def advance_uid_floor(self) -> None:
        """Advance the process-wide uid counter past every stored object's
        uid sequence, so the next create() can never mint a uid that
        collides with a recovered/replicated object's (controllers key
        liveness on uid). The one re-anchor implementation, shared by
        restore() (journal recovery) and promotion (apply_replicated
        preserves the PRIMARY's uids without tracking a running max)."""
        import itertools as _it
        import re as _re

        from training_operator_tpu.api.jobs import ObjectMeta

        with self._lock:
            max_seq = 0
            for obj in self._objects.values():
                m = _re.search(r"-(\d+)$", obj.metadata.uid or "")
                if m:
                    max_seq = max(max_seq, int(m.group(1)))
            if max_seq:
                # Class-level counter: all stores in-process share it, so
                # only ever advance it.
                current = next(ObjectMeta._uid_counter)
                ObjectMeta._uid_counter = _it.count(max(current, max_seq + 1))

    # -- admission ---------------------------------------------------------

    def register_admission(self, kind: str, fn: Callable[[Any], None]) -> None:
        self._admission.setdefault(kind, []).append(fn)

    def unregister_admission(self, kind: str, fn: Callable[[Any], None]) -> None:
        hooks = self._admission.get(kind)
        if hooks is not None and fn in hooks:
            hooks.remove(fn)

    # -- watch -------------------------------------------------------------

    def unwatch(self, queue: WatchQueue) -> None:
        """Detach a watcher (component shutdown) — without this a dead
        component's queue keeps accumulating cloned events forever."""
        with self._lock:
            if queue in self._watchers:
                self._watchers.remove(queue)

    def watch(self, kinds: Optional[Iterable[str]] = None) -> WatchQueue:
        wq = WatchQueue(kinds)
        with self._lock:
            self._watchers.append(wq)
        return wq

    def _next_rv(self) -> int:
        self._rv_value += 1
        return self._rv_value

    def version(self) -> int:
        """Global write counter — lets the cluster loop detect quiescence."""
        with self._lock:
            return self._rv_value

    def event_seq(self) -> int:
        """The last assigned watch-event sequence number — the 'now' a
        resume ring is born at (wire_server._ResumeRing)."""
        with self._lock:
            return self._event_seq

    def set_event_seq(self, seq: int) -> None:
        """Advance (never rewind) the watch-event sequence counter — the
        standby's bootstrap alignment: after restoring the primary's
        snapshot it pins its counter to the primary's, and from there every
        replicated put/del notifies exactly once (apply_replicated), so the
        two processes assign IDENTICAL seq numbers to identical events.
        That lockstep is what lets a promoted standby answer a surviving
        client's primary-epoch watermark with a delta instead of a relist."""
        with self._lock:
            self._event_seq = max(self._event_seq, int(seq))

    def object_counts(self) -> Dict[str, int]:
        """Live object count per kind — the fleet collector's store-size
        view, O(kinds) (the per-kind index already exists)."""
        with self._lock:
            return {
                kind: len(objs)
                for kind, objs in sorted(self._by_kind.items())
                if objs
            }

    def _notify(self, ev_type: str, obj: Any, status_only: bool = False) -> None:
        self._event_seq += 1
        ev = WatchEvent(
            ev_type, obj.KIND, obj, status_only=status_only, seq=self._event_seq
        )
        for w in self._watchers:
            w.push(ev)
        self._watch_cond.notify_all()

    def wait_and_drain(self, queue: WatchQueue, timeout: float = 0.0) -> List[WatchEvent]:
        """Block until `queue` has events (or `timeout` elapses), then drain.

        The cross-thread watch-consumer API: the HTTP wire's long-poll
        handler parks here on the store's condition variable, so a waiting
        watch client costs zero CPU between writes instead of a sleep-spin,
        and the drain is atomic with respect to concurrent pushes (both run
        under the store lock). In-process tick-driven consumers keep calling
        queue.drain() directly — they never want to block."""
        deadline = _time.monotonic() + timeout
        with self._watch_cond:
            while not len(queue):
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or not self._watch_cond.wait(remaining):
                    break
            return queue.drain()

    # -- CRUD --------------------------------------------------------------

    @staticmethod
    def _key(obj: Any) -> Tuple[str, str, str]:
        ns = getattr(obj.metadata, "namespace", "") or ""
        return (obj.KIND, ns, obj.metadata.name)

    def get_timeline(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        """One job's timeline as a wire-shaped dict (None when absent) —
        the same payload GET /timelines/{ns}/{name} serves, and the shape
        observe.export_chrome_trace consumes."""
        tl = self.timelines.timeline(namespace, name)
        return None if tl is None else tl.to_dict()

    def get_timelines(self, limit: int = 256) -> List[Dict[str, Any]]:
        """The newest retained timelines as wire-shaped dicts — the bulk
        feed GET /timelines serves, and what the merged chrome-trace export
        fans in per shard/replica. Capped: the LRU retains max_jobs, and a
        wire response walking all of them at 10k-job scale would be a
        self-inflicted LIST storm."""
        return [tl.to_dict() for tl in self.timelines.timelines()[-limit:]]

    def record_spans(
        self,
        namespace: str,
        name: str,
        spans: List[Dict[str, Any]],
        marks: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        """Bulk span/mark ingest (wire POST /timelines: a remote operator's
        manager pushes its queue-wait/reconcile spans to the host ring)."""
        for sd in spans:
            attrs = dict(sd.get("attrs", {}))
            uid = str(attrs.pop("uid", ""))
            # Client-chosen attr keys ride the `attrs` dict, never the
            # call signature — a span attr named "start" must not shadow
            # the parameter (or 500 the wire boundary).
            self.timelines.record_span(
                namespace, name, uid, str(sd.get("name", "")),
                start=float(sd.get("start", 0.0)),
                end=float(sd.get("end", 0.0)),
                wall=float(sd.get("wall", 0.0)),
                attrs=attrs,
            )
        for md in marks or []:
            self.timelines.mark(
                namespace, name, "", str(md.get("name", "")),
                t=float(md.get("t", 0.0)),
            )

    def create(self, obj: Any) -> Any:
        with self._lock:
            hooks = self._admission.get(obj.KIND, [])
            traced = hooks and _is_job_like(obj) and self.timelines.enabled
            if traced:
                t0 = _time.perf_counter()
            for fn in hooks:
                fn(obj)
            if traced:
                admission_wall = _time.perf_counter() - t0
                metrics.job_admission_seconds.observe(admission_wall)
                now = self.timelines.now()
                self.timelines.record_span(
                    getattr(obj.metadata, "namespace", "") or "",
                    obj.metadata.name,
                    obj.metadata.uid or "",
                    "admission",
                    start=now, end=now, wall=admission_wall, kind=obj.KIND,
                )
            key = self._key(obj)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            obj.metadata.ensure_uid(obj.KIND)
            obj.metadata.resource_version = self._next_rv()
            stored = self._clone(obj)
            # Write-ahead: journal BEFORE the in-memory apply and the watch
            # notify. A failed append (disk full) then aborts the write
            # cleanly — no watcher ever observes an object that won't
            # survive the restart the failure forces (see JournalWriteError).
            if self._journal is not None:
                self._journal("put", stored)
            self._objects[key] = stored
            self._by_kind.setdefault(key[0], {})[key[1:]] = stored
            self._index_labels(key, stored)
            self._notify("Added", self._clone(stored))
            return obj

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            try:
                return self._clone(self._objects[(kind, namespace or "", name)])
            except KeyError:
                raise NotFoundError(f"{kind} {namespace}/{name} not found") from None

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        with self._lock:
            obj = self._objects.get((kind, namespace or "", name))
            return self._clone(obj) if obj is not None else None

    def get_ref(self, kind: str, namespace: str, name: str) -> Any:
        """The STORED object, no copy — the wire encode fast path (a deep
        clone per GET would cost more than the serialization it feeds).
        Safe under the same invariant snapshot_refs leans on: updates
        replace stored objects, never mutate them in place, so a returned
        reference is a consistent frozen version forever. Callers must
        treat it as read-only."""
        with self._lock:
            try:
                return self._objects[(kind, namespace or "", name)]
            except KeyError:
                raise NotFoundError(f"{kind} {namespace}/{name} not found") from None

    def resource_version(self, kind: str, namespace: str, name: str) -> Optional[int]:
        """Version probe without the read copy — cache-validation fast path
        (a clone per probe would defeat the caches that key on this)."""
        with self._lock:
            obj = self._objects.get((kind, namespace or "", name))
            return obj.metadata.resource_version if obj is not None else None

    def update(self, obj: Any, check_version: bool = True, status_only: bool = False,
               coalesce: bool = True) -> Any:
        # `coalesce` is part of the APIServer duck-type for the wire
        # client's sake (RemoteAPIServer.update): in-process writes are
        # always synchronous, so it is accepted and ignored here.
        with self._lock:
            key = self._key(obj)
            current = self._objects.get(key)
            if current is None:
                raise NotFoundError(f"{key} not found")
            if check_version and (
                obj.metadata.resource_version != current.metadata.resource_version
            ):
                raise ConflictError(
                    f"{key}: stale resourceVersion {obj.metadata.resource_version} "
                    f"!= {current.metadata.resource_version}"
                )
            obj.metadata.resource_version = self._next_rv()
            stored = self._clone(obj)
            if self._journal is not None:  # write-ahead, see create()
                # status_only rides the journal record so a standby's
                # replicated watch events carry the same predicate (managers
                # skip re-enqueueing their own status echoes after failover).
                self._journal("put", stored, status_only)
            self._unindex_labels(key, current)
            self._objects[key] = stored
            self._by_kind.setdefault(key[0], {})[key[1:]] = stored
            self._index_labels(key, stored)
            self._notify("Modified", self._clone(stored), status_only=status_only)
            return obj

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            key = (kind, namespace or "", name)
            obj = self._objects.get(key)
            if obj is None:
                raise NotFoundError(f"{key} not found")
            if self._journal is not None:  # write-ahead, see create()
                self._journal("del", kind, namespace or "", name, self._rv_value)
            del self._objects[key]
            self._by_kind.get(kind, {}).pop(key[1:], None)
            self._unindex_labels(key, obj)
            if kind == "Pod":
                self._pod_logs.pop(key[1:], None)
            self._notify("Deleted", obj)  # orphaned: safe to hand out as-is
            return obj

    def try_delete(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        # Clone OUTSIDE the lock: the refs are frozen versions (updates
        # replace, never mutate), and the deep copies are the expensive
        # part — holding the store lock across them would stall every
        # concurrent API request at burst scale.
        return [self._clone(obj) for obj in self.list_refs(kind, namespace, label_selector)]

    def list_refs(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        limit: int = 0,
        after: Optional[Tuple[str, str]] = None,
    ) -> List[Any]:
        """list() without the copies — STORED references, read-only by the
        same contract as get_ref. The wire layer encodes these directly
        (and caches the bytes by resourceVersion), skipping one full deep
        copy per object per LIST.

        `limit`/`after` are the chunked-LIST support (apiserver limit/
        continue lineage): with limit > 0 the result is ordered by
        (namespace, name) and truncated to the first `limit` entries whose
        key sorts strictly after `after`. Key-ordered resumption is what
        makes a continue token stable under concurrent writes: an object
        neither created nor deleted during the walk is returned exactly
        once, because its sort position doesn't depend on the churn around
        it (unlike an offset, which shifts under every insert/delete)."""
        with self._lock:
            by_kind = self._by_kind.get(kind, {})
            if label_selector:
                # Intersect via the inverted index: start from the smallest
                # label bucket, verify remaining pairs per object.
                buckets = [
                    self._by_label.get((kind, lk, lv), set())
                    for lk, lv in label_selector.items()
                ]
                candidates = min(buckets, key=len) if buckets else set()
                out = []
                for subkey in candidates:
                    obj = by_kind.get(subkey)
                    if obj is None:
                        continue
                    if namespace is not None and subkey[0] != namespace:
                        continue
                    labels = obj.metadata.labels
                    if all(labels.get(lk) == lv for lk, lv in label_selector.items()):
                        out.append(obj)
            else:
                out = [
                    obj
                    for (ns, _), obj in by_kind.items()
                    if namespace is None or ns == namespace
                ]
        if limit > 0:
            # Sort + slice OUTSIDE the store lock: the captured refs are a
            # consistent snapshot (frozen versions), and a 10k-object walk
            # re-sorts per page — O(N log N) per page is tolerable off-lock
            # but would serialize every concurrent API call on-lock.
            out.sort(
                key=lambda o: (
                    getattr(o.metadata, "namespace", "") or "",
                    o.metadata.name,
                )
            )
            if after is not None:
                lo = 0
                hi = len(out)
                while lo < hi:  # first key strictly after the cursor
                    mid = (lo + hi) // 2
                    md = out[mid].metadata
                    if ((getattr(md, "namespace", "") or "", md.name)
                            <= after):
                        lo = mid + 1
                    else:
                        hi = mid
                out = out[lo:]
            out = out[:limit]
        return out

    # -- pod logs ----------------------------------------------------------

    def append_pod_log(self, namespace: str, name: str, line: str, ts: float = 0.0) -> None:
        """Kubelet-side write of one log line (lifecycle event or a line of
        container stdout) for pod namespace/name."""
        with self._lock:
            if self._journal is not None:  # write-ahead, see create()
                self._journal("log", namespace or "", name, str(line), ts)
            buf = self._pod_logs.setdefault(
                (namespace or "", name), {"lines": [], "base": 0}
            )
            for ln in str(line).splitlines() or [""]:
                buf["lines"].append((ts, ln))
            overflow = len(buf["lines"]) - self._pod_log_max
            if overflow > 0:
                del buf["lines"][:overflow]
                buf["base"] += overflow

    def read_pod_log(
        self,
        namespace: str,
        name: str,
        since: int = 0,
        tail: Optional[int] = None,
    ) -> Tuple[List[str], int]:
        """(formatted lines, next cursor). `since` is a cursor from a prior
        call (0 = start of retained log); pass it back to tail a running
        pod. `tail` limits to the last N retained lines."""
        with self._lock:
            buf = self._pod_logs.get((namespace or "", name))
            if buf is None:
                return [], since
            base, lines = buf["base"], buf["lines"]
            idx = max(0, since - base)
            out = lines[idx:]
            if tail is not None and len(out) > tail:
                out = out[-tail:]
            return [f"{ts:.3f} {ln}" for ts, ln in out], base + len(lines)

    # -- events ------------------------------------------------------------

    def _merge_event_locked(self, event: Event) -> None:
        """Append-or-aggregate one event (k8s Events parity): an identical
        repeat (same aggregation_key) becomes a count bump + last-timestamp
        move on a REPLACED record — stored events stay frozen versions (the
        snapshot/compaction path encodes captured references outside the
        lock), so aggregation replaces, never mutates in place."""
        import dataclasses as _dc

        key = event.aggregation_key()
        idx = self._event_index.get(key)
        if idx is not None:
            old = self._events[idx]
            self._events[idx] = _dc.replace(
                old,
                count=old.count + max(1, event.count),
                timestamp=event.timestamp or old.timestamp,
            )
            return
        if not event.first_timestamp:
            event.first_timestamp = event.timestamp
        event.count = max(1, event.count)
        self._event_index[key] = len(self._events)
        self._events_by_name.setdefault(event.object_name, []).append(
            len(self._events))
        self._events.append(event)
        if len(self._events) > self._event_cap:
            drop = len(self._events) - (self._event_cap * 3) // 4
            self._events = self._events[drop:]
            self._event_index = {
                e.aggregation_key(): i for i, e in enumerate(self._events)
            }
            self._events_by_name = {}
            for i, e in enumerate(self._events):
                self._events_by_name.setdefault(e.object_name, []).append(i)
            metrics.events_trimmed.inc(amount=drop)

    def record_event(self, event: Event) -> None:
        with self._lock:
            if self._journal is not None:  # write-ahead, see create()
                self._journal("event", event)
            self._merge_event_locked(event)

    def set_event_cap(self, cap: int) -> None:
        """Override the event-retention bound (>=1). A replication pair
        must agree on the cap — trimming is deterministic local state, so
        identical caps keep a standby's retained event list identical."""
        with self._lock:
            self._event_cap = max(1, int(cap))

    def event_cap(self) -> int:
        return self._event_cap

    def event_count(self) -> int:
        """Retained event records — the INV009 accumulator feed."""
        with self._lock:
            return len(self._events)

    def events(
        self, object_name: Optional[str] = None, reason: Optional[str] = None
    ) -> List[Event]:
        with self._lock:
            if object_name is not None:
                pool = [self._events[i]
                        for i in self._events_by_name.get(object_name, ())]
            else:
                pool = self._events
            return [
                e for e in pool
                if reason is None or e.reason == reason
            ]


def encode_snapshot(refs: Dict[str, Any]) -> Dict[str, Any]:
    """Wire-encode a snapshot_refs() capture (no lock needed: the captured
    references are immutable-by-convention — updates replace stored objects
    — and the event/log lists are copies)."""
    from training_operator_tpu.cluster import wire

    return {
        "rv": refs["rv"],
        "objects": [wire.encode(o) for o in refs["objects"]],
        "events": [wire.encode(e) for e in refs["events"]],
        "pod_logs": [
            {"ns": ns, "name": name, "base": base,
             "lines": [[ts, ln] for ts, ln in lines]}
            for ns, name, base, lines in refs["pod_logs"]
        ],
    }
