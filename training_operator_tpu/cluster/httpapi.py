"""HTTP/JSON wire boundary for the substrate API server — public facade.

Gives the in-process `APIServer` the same kind of process boundary the
reference control plane has everywhere: the SDK talks REST to a kube-apiserver
(reference training_client.py:41), the operator consumes watch streams across
a socket, and leader election is an apiserver-mediated lease race between real
processes (cmd/training-operator.v1/main.go:134-166). The pieces:

  ApiHTTPServer    — serves an existing APIServer over localhost HTTP
                     (CRUD + watch subscriptions + pod logs + events).
                     [wire_server.py]
  RemoteAPIServer  — client with the same duck-typed surface the engine and
                     SDK consume (create/get/try_get/list/update/delete/
                     try_delete/watch/unwatch/record_event/events/
                     read_pod_log/append_pod_log/resource_version).
                     [wire_transport.py]
  RemoteWatchQueue / CachedReadAPI
                   — client-side watch fanout over ONE shared wire session,
                     and the watch-fed lister cache. [wire_watch.py]
  RemoteRuntime    — the operator-side run loop (clock + tickers + timers),
                     shape-compatible with `Cluster` for OperatorManager and
                     TrainingClient, but backed by a RemoteAPIServer.
                     [wire_runtime.py]
  ShardedRemoteAPIServer
                   — the sharded-write-plane client: N per-shard
                     RemoteAPIServers behind the same surface, writes and
                     strong reads routed by (kind, namespace), watches
                     merged shard-scoped. [wire_shards.py]

This module carried all four concerns in one 1,300-line file until round 6;
it is now the import surface only. Everything the rest of the tree (and
tests, examples, the SDK) needs is re-exported here — import from
`cluster.httpapi`, never from the wire_* modules' underscore internals
(codelint rule CL004 enforces the seam).

Errors round-trip as HTTP statuses: 404 NotFound, 409 Conflict (stale
resourceVersion) / AlreadyExists (create), 422 admission rejection.
"""

from __future__ import annotations

from training_operator_tpu.cluster.wire_runtime import (
    RemoteRuntime,
    SyncedClock,
)
from training_operator_tpu.cluster.wire_server import ApiHTTPServer
from training_operator_tpu.cluster.wire_shards import ShardedRemoteAPIServer
from training_operator_tpu.cluster.wire_transport import (
    ApiServerError,
    ApiUnavailableError,
    RemoteAPIServer,
    RemoteTimelines,
)
from training_operator_tpu.cluster.wire_watch import (
    QUEUE_OVERFLOW,
    RELIST_RESET,
    CachedReadAPI,
    RemoteWatchQueue,
    ShardRelistReset,
)

__all__ = [
    "ApiHTTPServer",
    "ApiServerError",
    "ApiUnavailableError",
    "CachedReadAPI",
    "QUEUE_OVERFLOW",
    "RELIST_RESET",
    "RemoteAPIServer",
    "RemoteRuntime",
    "RemoteTimelines",
    "RemoteWatchQueue",
    "ShardRelistReset",
    "ShardedRemoteAPIServer",
    "SyncedClock",
]
