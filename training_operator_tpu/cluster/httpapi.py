"""HTTP/JSON wire boundary for the substrate API server.

Gives the in-process `APIServer` the same kind of process boundary the
reference control plane has everywhere: the SDK talks REST to a kube-apiserver
(reference training_client.py:41), the operator consumes watch streams across
a socket, and leader election is an apiserver-mediated lease race between real
processes (cmd/training-operator.v1/main.go:134-166). Three pieces:

  ApiHTTPServer    — serves an existing APIServer over localhost HTTP
                     (CRUD + watch subscriptions + pod logs + events).
  RemoteAPIServer  — client with the same duck-typed surface the engine and
                     SDK consume (create/get/try_get/list/update/delete/
                     try_delete/watch/unwatch/record_event/events/
                     read_pod_log/append_pod_log/resource_version).
  RemoteRuntime    — the operator-side run loop (clock + tickers + timers),
                     shape-compatible with `Cluster` for OperatorManager and
                     TrainingClient, but backed by a RemoteAPIServer.

Errors round-trip as HTTP statuses: 404 NotFound, 409 Conflict (stale
resourceVersion) / AlreadyExists (create), 422 admission rejection.

Watch sessions are server-side WatchQueues keyed by a token; clients poll
`GET /watches/<id>` (optionally long-polling via ?timeout=). Sessions idle
longer than `session_ttl` are garbage-collected so a kill -9'd operator
doesn't leak an ever-growing event queue.
"""

from __future__ import annotations

import heapq
import itertools
import json
import logging
import threading
import time as _time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.apiserver import (
    AlreadyExistsError,
    APIServer,
    ConflictError,
    NotFoundError,
    WatchQueue,
)
from training_operator_tpu.cluster.objects import Event
from training_operator_tpu.cluster.runtime import Clock

log = logging.getLogger(__name__)


class ApiUnavailableError(Exception):
    """Transport-level failure reaching the serving host (connection refused/
    reset, socket timeout). Distinct from the API-semantic errors so callers
    can retry instead of dying — a transient host hiccup must not take down
    both the leader AND the standby operator."""


class ApiServerError(Exception):
    """The host answered 5xx (handler exception, overload). Retryable like
    a transport failure — but a DISTINCT type from RuntimeError so the
    operator loop's retry arm cannot swallow genuine local bugs."""


# Empty namespace (cluster-scoped objects: Node, ClusterTrainingRuntime,
# leases in "" if anyone does that) can't travel as an empty URL path
# segment; "-" is the on-the-wire placeholder ("-" can never be a real
# namespace: RFC1035 labels must start with a letter).
def _ns_seg(namespace: str) -> str:
    return namespace or "-"


def _seg_ns(segment: str) -> str:
    return "" if segment == "-" else segment


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ApiHTTPServer:
    """Serve one APIServer over HTTP on a background thread.

    The owning process keeps driving its Cluster loop; handler threads only
    touch the APIServer, whose RLock makes every call atomic. Watch events
    pushed by handler-thread writes are drained by local tickers on the next
    step, identical to any other writer.
    """

    def __init__(
        self,
        api: APIServer,
        port: int = 0,
        bind: str = "127.0.0.1",
        session_ttl: float = 120.0,
        token: Optional[str] = None,
    ):
        """`token`: require `Authorization: Bearer <token>` on every route
        except /healthz and /readyz (probes stay open, like kubelet probes)
        — the secure-serving analogue of the reference's cert-gated
        apiserver connection (pkg/cert/cert.go:45), minus the rotation an
        in-process CA would be theater for."""
        self.api = api
        self.session_ttl = session_ttl
        self.token = token
        # watch_id -> (WatchQueue, last_access_monotonic)
        self._sessions: Dict[str, List[Any]] = {}
        self._sessions_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: Any) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> Any:
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b"{}"
                return json.loads(raw or b"{}")

            def _route(self, method: str) -> None:
                try:
                    parsed = urllib.parse.urlsplit(self.path)
                    parts = [p for p in parsed.path.split("/") if p]
                    q = dict(urllib.parse.parse_qsl(parsed.query))
                    outer._dispatch(self, method, parts, q)
                except NotFoundError as e:
                    self._send(404, {"error": "NotFound", "message": str(e)})
                except ConflictError as e:
                    self._send(409, {"error": "Conflict", "message": str(e)})
                except AlreadyExistsError as e:
                    self._send(409, {"error": "AlreadyExists", "message": str(e)})
                except ValueError as e:
                    self._send(422, {"error": "Invalid", "message": str(e)})
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001 — wire boundary
                    log.exception("httpapi handler error")
                    self._send(500, {"error": "Internal", "message": str(e)})

            def do_GET(self):
                self._route("GET")

            def do_POST(self):
                self._route("POST")

            def do_PUT(self):
                self._route("PUT")

            def do_DELETE(self):
                self._route("DELETE")

        class _Server(ThreadingHTTPServer):
            # Default listen backlog (5) is too small for several clients
            # opening a fresh connection per request. Subclass, not a class-
            # attribute mutation on the stdlib type, so unrelated servers in
            # this process keep their own backlog.
            request_queue_size = 64
            daemon_threads = True

        self._httpd = _Server((bind, port), Handler)
        self.port = self._httpd.server_address[1]
        self.url = f"http://{bind}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        # Background session GC: route-handler GC alone never runs once the
        # last watch client dies (kill -9 both operators), and the dead
        # sessions' queues would then accumulate every write's event until
        # OOM. A daemon timer sweeps regardless of request traffic.
        self._gc_stop = threading.Event()

        def _gc_loop():
            while not self._gc_stop.wait(min(30.0, max(1.0, session_ttl / 4))):
                self._gc_sessions()

        self._gc_thread = threading.Thread(target=_gc_loop, daemon=True)
        self._gc_thread.start()

    def close(self) -> None:
        self._gc_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, h, method: str, parts: List[str], q: Dict[str, str]) -> None:
        if not parts:
            h._send(404, {"error": "NotFound", "message": "no route"})
            return
        head = parts[0]
        if head in ("healthz", "readyz"):
            h._send(200, {"ok": True})
            return
        if self.token is not None:
            import hmac

            supplied = h.headers.get("Authorization", "")
            if not hmac.compare_digest(
                supplied.encode(), f"Bearer {self.token}".encode()
            ):
                h._send(401, {"error": "Unauthorized", "message": "bad or missing bearer token"})
                return
        if head == "objects":
            self._objects(h, method, parts[1:], q)
        elif head == "watches":
            self._watches(h, method, parts[1:], q)
        elif head == "logs":
            self._logs(h, method, parts[1:], q)
        elif head == "events":
            self._events(h, method, q)
        elif head == "version" and len(parts) == 4:
            rv = self.api.resource_version(parts[1], _seg_ns(parts[2]), parts[3])
            h._send(200, {"resourceVersion": rv})
        else:
            h._send(404, {"error": "NotFound", "message": f"no route {head}"})

    def _objects(self, h, method: str, parts: List[str], q: Dict[str, str]) -> None:
        if method == "POST" and not parts:
            obj = wire.decode(h._body())
            created = self.api.create(obj)
            h._send(201, wire.encode(created))
        elif method == "GET" and len(parts) == 1:
            selector = None
            if q.get("labelSelector"):
                selector = dict(
                    pair.split("=", 1) for pair in q["labelSelector"].split(",") if "=" in pair
                )
            objs = self.api.list(parts[0], q.get("namespace") or None, selector)
            h._send(200, {"items": [wire.encode(o) for o in objs]})
        elif method == "GET" and len(parts) == 3:
            h._send(200, wire.encode(self.api.get(parts[0], _seg_ns(parts[1]), parts[2])))
        elif method == "PUT" and len(parts) == 3:
            obj = wire.decode(h._body())
            updated = self.api.update(
                obj,
                check_version=q.get("check_version", "1") != "0",
                status_only=q.get("status_only") == "1",
            )
            h._send(200, wire.encode(updated))
        elif method == "DELETE" and len(parts) == 3:
            gone = self.api.delete(parts[0], _seg_ns(parts[1]), parts[2])
            h._send(200, wire.encode(gone))
        else:
            h._send(404, {"error": "NotFound", "message": "bad objects route"})

    def _watches(self, h, method: str, parts: List[str], q: Dict[str, str]) -> None:
        self._gc_sessions()
        if method == "POST" and not parts:
            body = h._body()
            kinds = body.get("kinds")
            wq = self.api.watch(kinds=kinds)
            wid = uuid.uuid4().hex
            with self._sessions_lock:
                self._sessions[wid] = [wq, _time.monotonic()]
            h._send(201, {"watch_id": wid})
        elif method == "GET" and len(parts) == 1:
            with self._sessions_lock:
                session = self._sessions.get(parts[0])
                if session is not None:
                    session[1] = _time.monotonic()
            if session is None:
                raise NotFoundError(f"watch session {parts[0]}")
            wq = session[0]
            timeout = float(q.get("timeout", "0"))
            deadline = _time.monotonic() + timeout
            while not len(wq) and _time.monotonic() < deadline:
                _time.sleep(0.01)
            # Drain under the API lock: pushes happen while writers hold it,
            # so this cannot race a concurrent push mid-drain.
            with self.api._lock:
                events = wq.drain()
            h._send(200, {"events": [wire.encode_watch_event(ev) for ev in events]})
        elif method == "DELETE" and len(parts) == 1:
            with self._sessions_lock:
                session = self._sessions.pop(parts[0], None)
            if session is not None:
                self.api.unwatch(session[0])
            h._send(200, {"ok": True})
        else:
            h._send(404, {"error": "NotFound", "message": "bad watches route"})

    def _gc_sessions(self) -> None:
        now = _time.monotonic()
        dead: List[Tuple[str, WatchQueue]] = []
        with self._sessions_lock:
            for wid, (wq, last) in list(self._sessions.items()):
                if now - last > self.session_ttl:
                    dead.append((wid, wq))
                    del self._sessions[wid]
        for _, wq in dead:
            self.api.unwatch(wq)

    def _logs(self, h, method: str, parts: List[str], q: Dict[str, str]) -> None:
        if len(parts) != 2:
            raise NotFoundError("logs route is /logs/<ns>/<pod>")
        ns, name = _seg_ns(parts[0]), parts[1]
        if method == "GET":
            tail = int(q["tail"]) if q.get("tail") else None
            lines, cursor = self.api.read_pod_log(
                ns, name, since=int(q.get("since", "0")), tail=tail
            )
            h._send(200, {"lines": lines, "cursor": cursor})
        elif method == "POST":
            body = h._body()
            self.api.append_pod_log(ns, name, body.get("line", ""), body.get("ts", 0.0))
            h._send(200, {"ok": True})
        else:
            raise NotFoundError("bad logs method")

    def _events(self, h, method: str, q: Dict[str, str]) -> None:
        if method == "POST":
            ev = wire.decode(h._body(), Event)
            self.api.record_event(ev)
            h._send(201, {"ok": True})
        else:
            evs = self.api.events(q.get("object_name") or None, q.get("reason") or None)
            h._send(200, {"items": [wire.encode(e) for e in evs]})


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class RemoteWatchQueue:
    """Client-side handle on a server watch session.

    `drain()` long-polls by default (`poll_timeout`): the server returns
    immediately when events are pending and holds the request briefly when
    none are — so an idle operator loop costs a few requests per second
    instead of busy-polling an empty queue at tick rate, while event
    delivery latency stays at one RTT."""

    def __init__(
        self,
        remote: "RemoteAPIServer",
        watch_id: str,
        kinds: Optional[List[str]] = None,
        poll_timeout: float = 0.25,
    ):
        self._remote = remote
        self.watch_id = watch_id
        self.kinds = kinds
        self.poll_timeout = poll_timeout

    def drain(self, timeout: Optional[float] = None) -> List[Any]:
        t = self.poll_timeout if timeout is None else timeout
        try:
            payload = self._remote._request(
                "GET", f"/watches/{self.watch_id}", query={"timeout": str(t)}
            )
        except NotFoundError:
            # Session reaped server-side (we were paused past session_ttl).
            # Re-subscribe in place; events missed in between are healed by
            # the consumer's periodic resync, exactly like an informer
            # relist after a dropped watch connection.
            fresh = self._remote.watch(self.kinds)
            self.watch_id = fresh.watch_id
            return []
        return [wire.decode_watch_event(d) for d in payload["events"]]

    def __len__(self) -> int:  # pragma: no cover - parity with WatchQueue
        return 0


class RemoteAPIServer:
    """APIServer duck-type speaking the wire protocol.

    Admission (`register_admission`) is a no-op here: validation and
    defaulting are enforced inside the serving process, exactly as k8s
    admission runs server-side no matter which client connects.
    """

    def __init__(self, base_url: str, timeout: float = 30.0, token: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token

    # -- transport ---------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        query: Optional[Dict[str, str]] = None,
    ) -> Any:
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        if self.token is not None:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(url, data=data, method=method, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            # HTTPError subclasses URLError — map the API-semantic statuses
            # before the transport-failure arm below can swallow them.
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                payload = {}
            kind = payload.get("error", "")
            msg = payload.get("message", str(e))
            if e.code == 404:
                raise NotFoundError(msg) from None
            if e.code == 409 and kind == "AlreadyExists":
                raise AlreadyExistsError(msg) from None
            if e.code == 409:
                raise ConflictError(msg) from None
            if e.code == 422:
                raise ValueError(msg) from None
            if e.code == 401:
                # Auth failures are config errors, not transients — the
                # operator loop must NOT retry these silently forever.
                raise PermissionError(msg) from None
            raise ApiServerError(f"{method} {path}: {e.code} {msg}") from None
        except (urllib.error.URLError, OSError) as e:
            # Connection refused/reset, DNS, socket timeout: retryable.
            raise ApiUnavailableError(f"{method} {path}: {e}") from None

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        out = wire.decode(self._request("POST", "/objects", body=wire.encode(obj)))
        # The caller's object carries the assigned uid/resourceVersion after
        # create (in-process contract), but the RETURNED object is the
        # server's stored state — including server-side admission mutations
        # (defaulting) the local copy never saw.
        obj.metadata.uid = out.metadata.uid
        obj.metadata.resource_version = out.metadata.resource_version
        return out

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return wire.decode(
            self._request("GET", f"/objects/{kind}/{_ns_seg(namespace)}/{name}")
        )

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        query: Dict[str, str] = {}
        if namespace is not None:
            query["namespace"] = namespace
        if label_selector:
            query["labelSelector"] = ",".join(f"{k}={v}" for k, v in label_selector.items())
        payload = self._request("GET", f"/objects/{kind}", query=query or None)
        return [wire.decode(d) for d in payload["items"]]

    def update(self, obj: Any, check_version: bool = True, status_only: bool = False) -> Any:
        ns = getattr(obj.metadata, "namespace", "") or ""
        out = wire.decode(
            self._request(
                "PUT",
                f"/objects/{obj.KIND}/{_ns_seg(ns)}/{obj.metadata.name}",
                body=wire.encode(obj),
                query={
                    "check_version": "1" if check_version else "0",
                    "status_only": "1" if status_only else "0",
                },
            )
        )
        obj.metadata.resource_version = out.metadata.resource_version
        return out

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        return wire.decode(
            self._request("DELETE", f"/objects/{kind}/{_ns_seg(namespace)}/{name}")
        )

    def try_delete(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except NotFoundError:
            return None

    def resource_version(self, kind: str, namespace: str, name: str) -> Optional[int]:
        return self._request("GET", f"/version/{kind}/{_ns_seg(namespace)}/{name}")[
            "resourceVersion"
        ]

    # -- watch -------------------------------------------------------------

    def watch(self, kinds: Optional[List[str]] = None) -> RemoteWatchQueue:
        payload = self._request(
            "POST", "/watches", body={"kinds": list(kinds) if kinds else None}
        )
        return RemoteWatchQueue(
            self, payload["watch_id"], kinds=list(kinds) if kinds else None
        )

    def unwatch(self, queue: RemoteWatchQueue) -> None:
        try:
            self._request("DELETE", f"/watches/{queue.watch_id}")
        except (NotFoundError, ApiUnavailableError, ApiServerError):
            pass  # best effort; the server GC reaps stale sessions anyway

    # -- admission ---------------------------------------------------------

    def register_admission(self, kind: str, fn: Callable[[Any], None]) -> None:
        pass  # server-side concern (see class docstring)

    def unregister_admission(self, kind: str, fn: Callable[[Any], None]) -> None:
        pass

    # -- logs / events -----------------------------------------------------

    def append_pod_log(self, namespace: str, name: str, line: str, ts: float = 0.0) -> None:
        self._request(
            "POST", f"/logs/{_ns_seg(namespace)}/{name}", body={"line": line, "ts": ts}
        )

    def read_pod_log(
        self, namespace: str, name: str, since: int = 0, tail: Optional[int] = None
    ) -> Tuple[List[str], int]:
        query = {"since": str(since)}
        if tail is not None:
            query["tail"] = str(tail)
        payload = self._request("GET", f"/logs/{_ns_seg(namespace)}/{name}", query=query)
        return payload["lines"], payload["cursor"]

    def record_event(self, event: Event) -> None:
        self._request("POST", "/events", body=wire.encode(event))

    def events(
        self, object_name: Optional[str] = None, reason: Optional[str] = None
    ) -> List[Event]:
        query: Dict[str, str] = {}
        if object_name:
            query["object_name"] = object_name
        if reason:
            query["reason"] = reason
        payload = self._request("GET", "/events", query=query or None)
        return [wire.decode(d, Event) for d in payload["items"]]


# ---------------------------------------------------------------------------
# Operator-side runtime
# ---------------------------------------------------------------------------


class RemoteRuntime:
    """Run loop for a process whose API server lives elsewhere.

    Shape-compatible with `Cluster` for everything the operator stack and
    the SDK consume (`api`, `clock`, `add_ticker`/`remove_ticker`,
    `schedule_at`/`schedule_after`, `run_until`/`run_for`, `live`), but with
    no local store, scheduler, or kubelet — those live in the serving
    process. Always real-clock: across OS processes there is no shared
    virtual time.
    """

    def __init__(self, api: RemoteAPIServer, tick_interval: float = 0.02):
        self.api = api
        self.clock = Clock()
        self.tick_interval = tick_interval
        self._tickers: List[Callable[[], None]] = []
        self._timers: List[Tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = itertools.count()

    def add_ticker(self, fn: Callable[[], None]) -> None:
        self._tickers.append(fn)

    def remove_ticker(self, fn: Callable[[], None]) -> None:
        try:
            self._tickers.remove(fn)
        except ValueError:
            pass

    def schedule_at(self, t: float, fn: Callable[[], None]) -> None:
        heapq.heappush(self._timers, (t, next(self._timer_seq), fn))

    def schedule_after(self, dt: float, fn: Callable[[], None]) -> None:
        self.schedule_at(self.clock.now() + dt, fn)

    def live(self, obj: Any) -> Any:
        ns = getattr(obj.metadata, "namespace", "") or ""
        return self.api.try_get(obj.KIND, ns, obj.metadata.name)

    def step(self) -> None:
        now = self.clock.now()
        while self._timers and self._timers[0][0] <= now:
            _, _, fn = heapq.heappop(self._timers)
            fn()
        for fn in list(self._tickers):
            fn()

    def run_until(self, predicate: Callable[[], bool], timeout: float = 30.0) -> bool:
        deadline = self.clock.now() + timeout
        while True:
            if predicate():
                return True
            self.step()
            if predicate():
                return True
            if self.clock.now() >= deadline:
                return False
            _time.sleep(self.tick_interval)

    def run_for(self, seconds: float) -> None:
        self.run_until(lambda: False, timeout=seconds)

    def run_forever(self, stop: threading.Event) -> None:
        """Operator main loop: a transient transport failure (host restart,
        connection reset) is survived with backoff — the process must NOT
        die, or one API hiccup would take out leader and standby together.
        Leadership safety doesn't depend on this: an unrenewable lease just
        expires and the healthiest candidate re-acquires."""
        backoff = 0.1
        while not stop.is_set():
            try:
                self.step()
                backoff = 0.1
            except (ApiUnavailableError, ApiServerError) as e:
                # Transport down, or the server answered 5xx — equally
                # transient from here (k8s clients retry 500s the same
                # way). Anything else — including plain RuntimeError from
                # local code — is a bug and crashes loudly.
                log.warning("API server error (%s); retrying in %.1fs", e, backoff)
                _time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            _time.sleep(self.tick_interval)
