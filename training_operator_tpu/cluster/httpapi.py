"""HTTP/JSON wire boundary for the substrate API server.

Gives the in-process `APIServer` the same kind of process boundary the
reference control plane has everywhere: the SDK talks REST to a kube-apiserver
(reference training_client.py:41), the operator consumes watch streams across
a socket, and leader election is an apiserver-mediated lease race between real
processes (cmd/training-operator.v1/main.go:134-166). Three pieces:

  ApiHTTPServer    — serves an existing APIServer over localhost HTTP
                     (CRUD + watch subscriptions + pod logs + events).
  RemoteAPIServer  — client with the same duck-typed surface the engine and
                     SDK consume (create/get/try_get/list/update/delete/
                     try_delete/watch/unwatch/record_event/events/
                     read_pod_log/append_pod_log/resource_version).
  RemoteRuntime    — the operator-side run loop (clock + tickers + timers),
                     shape-compatible with `Cluster` for OperatorManager and
                     TrainingClient, but backed by a RemoteAPIServer.

Errors round-trip as HTTP statuses: 404 NotFound, 409 Conflict (stale
resourceVersion) / AlreadyExists (create), 422 admission rejection.

Watch sessions are server-side WatchQueues keyed by a token; clients poll
`GET /watches/<id>` (optionally long-polling via ?timeout=). Sessions idle
longer than `session_ttl` are garbage-collected so a kill -9'd operator
doesn't leak an ever-growing event queue.
"""

from __future__ import annotations

import heapq
import http.client
import itertools
import json
import logging
import socket
import ssl as _ssl
import threading
import time as _time
import urllib.parse
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.apiserver import (
    AlreadyExistsError,
    APIServer,
    ConflictError,
    NotFoundError,
    WatchQueue,
)
from training_operator_tpu.cluster.objects import Event
from training_operator_tpu.cluster.runtime import Clock
from training_operator_tpu.utils import metrics

log = logging.getLogger(__name__)


class ApiUnavailableError(Exception):
    """Transport-level failure reaching the serving host (connection refused/
    reset, socket timeout). Distinct from the API-semantic errors so callers
    can retry instead of dying — a transient host hiccup must not take down
    both the leader AND the standby operator."""


class ApiServerError(Exception):
    """The host answered 5xx (handler exception, overload). Retryable like
    a transport failure — but a DISTINCT type from RuntimeError so the
    operator loop's retry arm cannot swallow genuine local bugs."""


# Empty namespace (cluster-scoped objects: Node, ClusterTrainingRuntime,
# leases in "" if anyone does that) can't travel as an empty URL path
# segment; "-" is the on-the-wire placeholder ("-" can never be a real
# namespace: RFC1035 labels must start with a letter).
def _ns_seg(namespace: str) -> str:
    return _quote_seg(namespace or "-")


# Names are never validated against RFC1123, so a '/', '?', '#', space, or
# non-ASCII in a name must ride as percent-encoding — otherwise the object
# routes wrongly (create succeeds, get/update/delete 404).
def _quote_seg(segment: str) -> str:
    return urllib.parse.quote(str(segment), safe="")


def _seg_ns(segment: str) -> str:
    return "" if segment == "-" else segment


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ApiHTTPServer:
    """Serve one APIServer over HTTP on a background thread.

    The owning process keeps driving its Cluster loop; handler threads only
    touch the APIServer, whose RLock makes every call atomic. Watch events
    pushed by handler-thread writes are drained by local tickers on the next
    step, identical to any other writer.
    """

    def __init__(
        self,
        api: APIServer,
        port: int = 0,
        bind: str = "127.0.0.1",
        session_ttl: float = 120.0,
        token: Optional[str] = None,
        now_fn: Optional[Callable[[], float]] = None,
        tls: Optional[Tuple[str, str]] = None,
        chaos: Optional[object] = None,
    ):
        """`token`: require `Authorization: Bearer <token>` on every route
        except /healthz and /readyz (probes stay open, like kubelet probes)
        — the authn half of the reference's cert-gated apiserver connection
        (pkg/cert/cert.go:45); the transport half is TLS (see `certs.py`).

        `now_fn`: the serving process's cluster clock, exposed at GET /time
        so remote operators can run their lease/TTL arithmetic on HOST time
        (SyncedClock). Leases written by operators on different machines
        would otherwise compare renew_time against incomparable per-machine
        monotonic epochs — takeover permanently blocked, or split-brain.

        `tls`: (cert_path, key_path) pair (see certs.mint_server_cert) —
        serve HTTPS; the cert can be hot-rotated via rotate_cert().

        `chaos`: a cluster.chaos.WireChaos policy — per-request transport
        fault injection (5xx, connection reset, watch-session reap) for
        adversarial testing of the client retry/resubscribe arms."""
        self.api = api
        self.session_ttl = session_ttl
        self.token = token
        self.chaos = chaos
        self.now_fn = now_fn or _time.time
        if token and tls is None and bind not in ("127.0.0.1", "::1", "localhost"):
            log.warning(
                "bearer token configured on a non-loopback cleartext bind "
                "(%s): the token and all API traffic are sniffable; serve "
                "TLS (--tls) for non-local deployments", bind,
            )
        # watch_id -> (WatchQueue, last_access_monotonic)
        self._sessions: Dict[str, List[Any]] = {}
        self._sessions_lock = threading.Lock()
        # Version-keyed body cache: (kind, ns, name, resourceVersion) ->
        # encoded JSON bytes. Objects are immutable between resourceVersions
        # (copy-on-read store), so cached bytes can never be stale — an
        # update bumps the rv and misses. GET serves straight from bytes;
        # LIST responses are assembled by byte concatenation. LRU-bounded:
        # dead versions age out, no invalidation hooks needed.
        self._body_cache: "OrderedDict[Tuple[str, str, str, int], bytes]" = OrderedDict()
        self._body_cache_max = 16384
        self._body_lock = threading.Lock()
        # Parsed-route memo keyed by the raw request target: watch polls and
        # burst-time LISTs repeat identical paths thousands of times, and
        # urlsplit+unquote+parse_qsl per request shows up at that scale.
        # Handlers never mutate the parts/query they are handed. Unlocked by
        # design: a lost race costs one re-parse, nothing else.
        self._route_cache: Dict[str, Tuple[List[str], Dict[str, str]]] = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Response headers and body go out as separate send()s; with
            # Nagle on a keep-alive connection the second segment waits on
            # the client's delayed ACK — a flat ~40ms tax on EVERY request.
            disable_nagle_algorithm = True

            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: Any) -> None:
                self._send_bytes(code, json.dumps(payload).encode())

            def _send_bytes(self, code: int, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> Any:
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b"{}"
                return json.loads(raw or b"{}")

            def _route(self, method: str) -> None:
                try:
                    cached = outer._route_cache.get(self.path)
                    if cached is None:
                        parsed = urllib.parse.urlsplit(self.path)
                        # Unquote AFTER splitting: a %2F inside an object
                        # name must not become a path separator.
                        parts = [
                            urllib.parse.unquote(p)
                            for p in parsed.path.split("/")
                            if p
                        ]
                        q = dict(urllib.parse.parse_qsl(parsed.query))
                        # Inserted by _dispatch only AFTER auth passes —
                        # unauthenticated traffic must not evict hot routes
                        # or pin attacker-chosen keys.
                        outer._dispatch(self, method, parts, q, memo_key=self.path)
                    else:
                        parts, q = cached
                        outer._dispatch(self, method, parts, q)
                except NotFoundError as e:
                    self._send(404, {"error": "NotFound", "message": str(e)})
                except ConflictError as e:
                    self._send(409, {"error": "Conflict", "message": str(e)})
                except AlreadyExistsError as e:
                    self._send(409, {"error": "AlreadyExists", "message": str(e)})
                except ValueError as e:
                    self._send(422, {"error": "Invalid", "message": str(e)})
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001 — wire boundary
                    log.exception("httpapi handler error")
                    self._send(500, {"error": "Internal", "message": str(e)})

            def do_GET(self):
                self._route("GET")

            def do_POST(self):
                self._route("POST")

            def do_PUT(self):
                self._route("PUT")

            def do_DELETE(self):
                self._route("DELETE")

        class _Server(ThreadingHTTPServer):
            # Default listen backlog (5) is too small for several clients
            # opening a fresh connection per request. Subclass, not a class-
            # attribute mutation on the stdlib type, so unrelated servers in
            # this process keep their own backlog.
            request_queue_size = 64
            daemon_threads = True

            def handle_error(self, request, client_address):
                # TLS handshake failures (plain-HTTP probe against the HTTPS
                # port, cert rejected by a mis-pinned client) arrive here per
                # connection; stdlib prints a full traceback to stderr.
                log.debug("connection error from %s", client_address, exc_info=True)

        self._httpd = _Server((bind, port), Handler)
        self._ssl_context = None
        scheme = "http"
        if tls is not None:
            from training_operator_tpu.cluster import certs as _certs

            self._ssl_context = _certs.server_context(*tls)
            # Handshake deferred to the handler thread (first read), so a
            # slow client's handshake can't stall the accept loop.
            self._httpd.socket = self._ssl_context.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False,
            )
            scheme = "https"
        self.port = self._httpd.server_address[1]
        self.url = f"{scheme}://{bind}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        # Background session GC: route-handler GC alone never runs once the
        # last watch client dies (kill -9 both operators), and the dead
        # sessions' queues would then accumulate every write's event until
        # OOM. A daemon timer sweeps regardless of request traffic.
        self._gc_stop = threading.Event()

        def _gc_loop():
            while not self._gc_stop.wait(min(30.0, max(1.0, session_ttl / 4))):
                self._gc_sessions()

        self._gc_thread = threading.Thread(target=_gc_loop, daemon=True)
        self._gc_thread.start()

    def close(self) -> None:
        self._gc_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    def rotate_cert(self, cert_path: str, key_path: str) -> None:
        """Hot-rotate the serving cert: reload into the LIVE ssl context so
        new handshakes present the fresh cert while established connections
        finish on the old one. Clients pin the CA, not the serving cert, so
        rotation is invisible to them — the reference's rotated webhook
        serving certs behave the same way (pkg/cert/cert.go:45)."""
        if self._ssl_context is None:
            raise RuntimeError("server is not serving TLS")
        self._ssl_context.load_cert_chain(cert_path, key_path)
        log.info("rotated serving certificate from %s", cert_path)

    # -- dispatch ----------------------------------------------------------

    def _dispatch(
        self,
        h,
        method: str,
        parts: List[str],
        q: Dict[str, str],
        memo_key: Optional[str] = None,
    ) -> None:
        if not parts:
            h._send(404, {"error": "NotFound", "message": "no route"})
            return
        head = parts[0]
        if head in ("healthz", "readyz"):
            h._send(200, {"ok": True})
            return
        if head == "time":
            # Open like the probes: clock sync must work before a client
            # has its token plumbed, and the value is not sensitive.
            h._send(200, {"now": self.now_fn()})
            return
        if self.chaos is not None:
            action = self.chaos.sample()
            if action == "error":
                h._send(500, {"error": "Internal", "message": "chaos: injected"})
                return
            if action == "reset":
                # No response at all — the client sees a connection reset
                # (transport failure, not an API status).
                import socket as _socket

                try:
                    h.connection.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
                h.close_connection = True
                return
            if action == "reap":
                # Session loss (failover / memory pressure): every watch
                # client must resubscribe and heal by resync. The request
                # itself is then served normally.
                self._reap_all_sessions()
        if self.token is not None:
            import hmac

            supplied = h.headers.get("Authorization", "")
            if not hmac.compare_digest(
                supplied.encode(), f"Bearer {self.token}".encode()
            ):
                h._send(401, {"error": "Unauthorized", "message": "bad or missing bearer token"})
                return
        if memo_key is not None and len(memo_key) <= 512:
            # Authenticated (or open-deployment) request on a fresh path:
            # memoize the parse. Bounded; clear-all on overflow is fine —
            # the hot keys (watch polls, burst LISTs) repopulate instantly.
            if len(self._route_cache) >= 4096:
                self._route_cache.clear()
            self._route_cache[memo_key] = (parts, q)
        if head == "objects":
            self._objects(h, method, parts[1:], q)
        elif head == "watches":
            self._watches(h, method, parts[1:], q)
        elif head == "logs":
            self._logs(h, method, parts[1:], q)
        elif head == "events":
            self._events(h, method, q)
        elif head == "metrics":
            # JSON snapshot of the serving process's metrics registry —
            # how a remote bench/test reads the wire-cache hit rates
            # (codec/body/event counters) instead of trusting a self-run.
            h._send(200, metrics.registry.snapshot())
        elif head == "version" and len(parts) == 4:
            rv = self.api.resource_version(parts[1], _seg_ns(parts[2]), parts[3])
            h._send(200, {"resourceVersion": rv})
        else:
            h._send(404, {"error": "NotFound", "message": f"no route {head}"})

    def _object_bytes(self, obj) -> bytes:
        """Encoded JSON bytes for one STORED object reference, via the
        version-keyed cache. The ref is a frozen version (updates replace,
        never mutate), so encoding outside any lock is safe and the cached
        bytes are valid for that (name, resourceVersion) forever."""
        md = obj.metadata
        key = (
            obj.KIND,
            getattr(md, "namespace", "") or "",
            md.name,
            md.resource_version,
        )
        with self._body_lock:
            body = self._body_cache.get(key)
            if body is not None:
                self._body_cache.move_to_end(key)
        if body is not None:
            metrics.wire_body_cache_hits.inc()
            return body
        body = json.dumps(wire.encode(obj), separators=(",", ":")).encode()
        metrics.wire_body_cache_misses.inc()
        with self._body_lock:
            self._body_cache[key] = body
            while len(self._body_cache) > self._body_cache_max:
                self._body_cache.popitem(last=False)
        return body

    def _objects(self, h, method: str, parts: List[str], q: Dict[str, str]) -> None:
        if method == "POST" and not parts:
            obj = wire.decode(h._body())
            created = self.api.create(obj)
            # Respond through the body cache: `created` carries the assigned
            # uid/resourceVersion and is content-identical to the stored
            # clone, so this both serves the response and SEEDS the cache —
            # the operator's next LIST of this version is a hit.
            h._send_bytes(201, self._object_bytes(created))
        elif method == "GET" and len(parts) == 1:
            selector = None
            if q.get("labelSelector"):
                selector = dict(
                    pair.split("=", 1) for pair in q["labelSelector"].split(",") if "=" in pair
                )
            refs = self.api.list_refs(parts[0], q.get("namespace") or None, selector)
            # Byte concatenation, not re-encoding: each element's bytes come
            # from the version-keyed cache, so a burst of identical LISTs
            # costs one serialization per changed object, total.
            h._send_bytes(
                200,
                b'{"items":[' + b",".join(self._object_bytes(o) for o in refs) + b"]}",
            )
        elif method == "GET" and len(parts) == 3:
            h._send_bytes(
                200,
                self._object_bytes(self.api.get_ref(parts[0], _seg_ns(parts[1]), parts[2])),
            )
        elif method == "PUT" and len(parts) == 3:
            obj = wire.decode(h._body())
            updated = self.api.update(
                obj,
                check_version=q.get("check_version", "1") != "0",
                status_only=q.get("status_only") == "1",
            )
            # Seeds the cache with the fresh version (see POST above).
            h._send_bytes(200, self._object_bytes(updated))
        elif method == "DELETE" and len(parts) == 3:
            gone = self.api.delete(parts[0], _seg_ns(parts[1]), parts[2])
            # The deleted object's final version is usually already cached.
            h._send_bytes(200, self._object_bytes(gone))
        else:
            h._send(404, {"error": "NotFound", "message": "bad objects route"})

    def _watches(self, h, method: str, parts: List[str], q: Dict[str, str]) -> None:
        self._gc_sessions()
        if method == "POST" and not parts:
            body = h._body()
            kinds = body.get("kinds")
            wq = self.api.watch(kinds=kinds)
            wid = uuid.uuid4().hex
            with self._sessions_lock:
                self._sessions[wid] = [wq, _time.monotonic()]
            h._send(201, {"watch_id": wid})
        elif method == "GET" and len(parts) == 1:
            with self._sessions_lock:
                session = self._sessions.get(parts[0])
                if session is not None:
                    session[1] = _time.monotonic()
            if session is None:
                raise NotFoundError(f"watch session {parts[0]}")
            wq = session[0]
            # Clamp the client-supplied long-poll timeout well under the
            # session TTL: a poll allowed to outlive the TTL could have its
            # session GC'd mid-wait, dropping the buffered events it was
            # about to receive and forcing a needless resubscribe+resync.
            timeout = min(float(q.get("timeout", "0")), self.session_ttl / 4)
            # Park on the store's condition variable — zero CPU while idle,
            # wakes on the next write, drain atomic w.r.t. pushes.
            events = self.api.wait_and_drain(wq, timeout=timeout)
            with self._sessions_lock:
                session = self._sessions.get(parts[0])
                if session is not None:
                    session[1] = _time.monotonic()  # poll completion counts as activity
            # Serialize-once fanout: each event's bytes are encoded exactly
            # once (cached on the shared event object) and reused by every
            # session's drain — N subscribers no longer cost N encodes.
            h._send_bytes(
                200,
                b'{"events":['
                + b",".join(wire.encode_watch_event_bytes(ev) for ev in events)
                + b"]}",
            )
        elif method == "DELETE" and len(parts) == 1:
            with self._sessions_lock:
                session = self._sessions.pop(parts[0], None)
            if session is not None:
                self.api.unwatch(session[0])
            h._send(200, {"ok": True})
        else:
            h._send(404, {"error": "NotFound", "message": "bad watches route"})

    def _reap_all_sessions(self) -> None:
        with self._sessions_lock:
            dead = list(self._sessions.values())
            self._sessions.clear()
        for wq, _ in dead:
            self.api.unwatch(wq)

    def _gc_sessions(self) -> None:
        now = _time.monotonic()
        dead: List[Tuple[str, WatchQueue]] = []
        with self._sessions_lock:
            for wid, (wq, last) in list(self._sessions.items()):
                if now - last > self.session_ttl:
                    dead.append((wid, wq))
                    del self._sessions[wid]
        for _, wq in dead:
            self.api.unwatch(wq)

    def _logs(self, h, method: str, parts: List[str], q: Dict[str, str]) -> None:
        if len(parts) != 2:
            raise NotFoundError("logs route is /logs/<ns>/<pod>")
        ns, name = _seg_ns(parts[0]), parts[1]
        if method == "GET":
            tail = int(q["tail"]) if q.get("tail") else None
            lines, cursor = self.api.read_pod_log(
                ns, name, since=int(q.get("since", "0")), tail=tail
            )
            h._send(200, {"lines": lines, "cursor": cursor})
        elif method == "POST":
            body = h._body()
            self.api.append_pod_log(ns, name, body.get("line", ""), body.get("ts", 0.0))
            h._send(200, {"ok": True})
        else:
            raise NotFoundError("bad logs method")

    def _events(self, h, method: str, q: Dict[str, str]) -> None:
        if method == "POST":
            ev = wire.decode(h._body(), Event)
            self.api.record_event(ev)
            h._send(201, {"ok": True})
        else:
            evs = self.api.events(q.get("object_name") or None, q.get("reason") or None)
            h._send(200, {"items": [wire.encode(e) for e in evs]})


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


# Sentinel delivered (only to opt-in subscribers) at the head of a relist:
# "everything after this is the FULL current state — drop what you had".
# Without it, a mirror fed by Added/Modified/Deleted events can never learn
# about objects deleted while the watch session was lost: the relist only
# re-announces survivors, so ghosts would live in the cache forever.
RELIST_RESET = object()

# Sentinel left as the sole content of a fanout queue whose consumer stopped
# draining and let it hit its overflow limit: "your event history is gone —
# rebuild from authoritative lists". Only mirror-building consumers opt into
# bounded queues; for them a lost history is recoverable (re-prime), whereas
# silently dropping individual events would leave permanent ghosts.
QUEUE_OVERFLOW = object()


class RemoteWatchQueue:
    """Fanout handle on the client's ONE shared wire watch session.

    Early rounds gave every consumer its own server-side session; with
    several consumers per process (v1 manager + v2 manager), every idle
    tick serialized multiple empty long-polls — over a second of pure
    blocking per tick, a 12x submit->Running overhead on the wire vs
    in-process. This is the informer fix: one wire session per
    RemoteAPIServer (see _SharedWatch), events fanned out client-side by
    kind filter, and at most ONE blocking long-poll per block interval
    across all consumers. Matches the reference, where any number of
    controllers share one informer's watch connection per resource.

    `drain()` semantics are unchanged for consumers: returns pending
    events, long-polling briefly when idle; after a server-side session
    loss it transparently resubscribes and RELISTS (ListAndWatch), so
    lost events can delay work but never wedge it.
    """

    def __init__(self, shared: "_SharedWatch", kinds: Optional[List[str]] = None):
        from collections import deque

        self._shared = shared
        self.kinds = set(kinds) if kinds else None
        # Opt-in: receive RELIST_RESET at the head of a post-reconnect
        # relist. Mirror-building consumers (CachedReadAPI) need it;
        # event-driven consumers (the managers, whose periodic resync
        # re-enqueues work from authoritative lists) do not, and must not
        # have to know about the sentinel.
        self.reset_on_relist = False
        # Bound for consumers that may legitimately stop draining for long
        # stretches (a STANDBY operator never lists, so its lister cache
        # never drains — without a bound every cluster event would
        # accumulate in this deque for the whole standby lifetime). 0 = no
        # bound (tick-driven consumers drain every tick by construction).
        # On overflow the queue is collapsed to QUEUE_OVERFLOW.
        self.overflow_limit = 0
        self._local: "deque" = deque()

    def _append(self, item: Any) -> None:
        if self.overflow_limit and len(self._local) >= self.overflow_limit:
            if self._local and self._local[-1] is QUEUE_OVERFLOW:
                return
            self._local.clear()
            self._local.append(QUEUE_OVERFLOW)
            return
        self._local.append(item)

    @property
    def watch_id(self) -> Optional[str]:
        return self._shared.watch_id

    def drain(self, timeout: Optional[float] = None) -> List[Any]:
        return self._shared.drain_for(self, timeout)

    def poll_local(self) -> List[Any]:
        """Drain ONLY events already distributed to this queue — never hits
        the wire. For piggyback consumers (the lister cache) that ride the
        pumping some other consumer (the manager tick) is already doing."""
        with self._shared._lock:
            out = list(self._local)
            self._local.clear()
            return out

    def __len__(self) -> int:
        return len(self._local)


class _SharedWatch:
    """The one wire watch session a RemoteAPIServer multiplexes.

    The server session subscribes to ALL kinds (client-side filters do the
    narrowing): per-subscriber server sessions would resurrect the
    serialized-empty-poll problem this class exists to kill, and the
    operator-side consumers want all kinds anyway.

    Blocking policy: a drain may long-poll the wire only if no blocking
    poll happened within `min_block_interval` (one tick); otherwise an
    empty local queue returns [] immediately. Net effect: an idle process
    holds ONE cheap long-poll open per window (the server parks it on the
    store's condition variable — zero CPU both sides), and event delivery
    latency stays ~one RTT because the parked poll wakes on the write.
    """

    def __init__(
        self,
        remote: "RemoteAPIServer",
        poll_timeout: float = 0.25,
        min_block_interval: float = 0.02,
    ):
        self._remote = remote
        self.poll_timeout = poll_timeout
        self.min_block_interval = min_block_interval
        self.watch_id: Optional[str] = None
        self._subs: List[RemoteWatchQueue] = []
        self._needs_relist = False
        self._last_block = -float("inf")
        self._lock = threading.RLock()

    # -- subscriber management --------------------------------------------

    def subscribe(self, kinds: Optional[List[str]]) -> RemoteWatchQueue:
        with self._lock:
            q = RemoteWatchQueue(self, kinds)
            self._subs.append(q)
            if self.watch_id is None:
                self._open()
            return q

    def unsubscribe(self, q: RemoteWatchQueue) -> None:
        with self._lock:
            if q in self._subs:
                self._subs.remove(q)
            if not self._subs and self.watch_id is not None:
                wid, self.watch_id = self.watch_id, None
                try:
                    self._remote._request("DELETE", f"/watches/{wid}")
                except (NotFoundError, ApiUnavailableError, ApiServerError,
                        PermissionError):
                    pass  # server GC reaps stale sessions anyway

    def _open(self) -> None:
        payload = self._remote._request("POST", "/watches", body={"kinds": None})
        self.watch_id = payload["watch_id"]

    # -- pumping ----------------------------------------------------------

    def drain_for(self, q: RemoteWatchQueue, timeout: Optional[float]) -> List[Any]:
        with self._lock:
            if q not in self._subs:
                # Drained after unwatch (or a fresh consumer of a dead
                # handle): rejoin, and heal the unobserved gap by relist.
                self._subs.append(q)
                self._needs_relist = True
            if not q._local:
                # Contract: an EXPLICIT timeout is an explicit fetch — it
                # always hits the wire. A bare drain() (the tick-loop form)
                # is subject to the block window: if some consumer blocked
                # within the last interval, pending events were already
                # distributed and the next tick's pump is <=interval away.
                if self._needs_relist:
                    self._pump(0.0)
                elif timeout is not None:
                    self._pump(timeout)
                elif (
                    _time.monotonic() - self._last_block
                    >= self.min_block_interval
                ):
                    self._pump(self.poll_timeout)
            out = list(q._local)
            q._local.clear()
            return out

    def _pump(self, t: float) -> None:
        if self.watch_id is None:
            self._open()
            self._needs_relist = True
        if self._needs_relist:
            self._relist()
            return
        if t > 0:
            # Count the attempt, success or not: a 5xx storm must not turn
            # every consumer's drain back into a serial blocking poll.
            self._last_block = _time.monotonic()
        try:
            payload = self._remote._request(
                "GET", f"/watches/{self.watch_id}", query={"timeout": str(t)},
                channel="watch", idempotent=False,
            )
        except ApiUnavailableError:
            # The drain died mid-flight on a transport failure. The server
            # may already have emptied the queue into the lost response —
            # those events are unrecoverable via the session, so the ONLY
            # safe recovery is a relist (marked now, run on the next drain).
            # A transparent GET retry here (the pre-fix behavior) would
            # return an empty drain and silently drop them instead.
            self._needs_relist = True
            raise
        except NotFoundError:
            # Session reaped server-side (idle past session_ttl, host
            # restart, injected chaos). Re-subscribe, then RELIST and
            # synthesize Added events for everything that exists — the
            # informer ListAndWatch contract on reconnect. Without the
            # relist, events lost in the gap (above all pod create-echoes)
            # would wedge the engine's expectations cache until its 5-min
            # TTL: a job-key resync re-ENQUEUES work but cannot OBSERVE
            # the pods the lost events carried.
            self._needs_relist = True
            self._open()
            self._relist()
            return
        for d in payload["events"]:
            self._distribute(wire.decode_watch_event(d))

    def _relist(self) -> List[Any]:
        """Synthesize Added events for the full current state. Watch is
        (re)opened BEFORE the lists, so an object written in between can be
        seen twice (consumers are idempotent; expectations tolerate
        over-observation) but never lost. Only a FULLY successful relist
        clears the flag — a 5xx mid-relist retries on the next drain."""
        from training_operator_tpu.cluster.apiserver import WatchEvent

        events = []
        for kind in wire.KIND_REGISTRY:
            for obj in self._remote.list(kind):
                events.append(WatchEvent("Added", kind, obj))
        self._needs_relist = False  # only cleared on a FULLY successful relist
        # Opt-in subscribers (mirror builders) get the reset marker FIRST:
        # what follows is the complete state, and anything they hold that
        # is absent from it was deleted while the session was down — its
        # Deleted event is gone forever.
        for q in self._subs:
            if q.reset_on_relist:
                q._append(RELIST_RESET)
        for ev in events:
            self._distribute(ev)
        return events

    def _distribute(self, ev: Any) -> None:
        # One shared decoded copy per event, same as the in-process
        # informer contract (apiserver.py module docstring).
        for q in self._subs:
            if q.kinds is None or ev.kind in q.kinds:
                q._append(ev)


class RemoteAPIServer:
    """APIServer duck-type speaking the wire protocol.

    Admission (`register_admission`) is a no-op here: validation and
    defaulting are enforced inside the serving process, exactly as k8s
    admission runs server-side no matter which client connects.
    """

    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
    ):
        """`ca_file`: PEM CA bundle to verify an https host against (the
        pin on the host-minted CA, certs.mint_ca). Without it an https URL
        is verified against the system trust store — which will reject a
        self-signed host CA, loudly, rather than silently not verifying."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token
        self.ca_file = ca_file
        self._shared_watch: Optional[_SharedWatch] = None
        self._local = threading.local()
        self._ssl_context = None
        # Request-path trims: the URL is parsed once and the header dict is
        # built once — a reconcile makes ~8 wire calls and a 1k-job burst
        # makes tens of thousands, so per-request urlsplit + dict rebuilds
        # are measurable. http.client copies headers into its send buffer
        # and never mutates the dict, so sharing one instance is safe.
        parsed = urllib.parse.urlsplit(self.base_url)
        self._host = parsed.hostname
        self._port = parsed.port
        self._scheme = parsed.scheme
        self._headers: Dict[str, str] = {"Content-Type": "application/json"}
        if token is not None:
            self._headers["Authorization"] = f"Bearer {token}"
        if self._scheme == "https":
            from training_operator_tpu.cluster import certs as _certs

            self._ssl_context = (
                _certs.client_context(ca_file) if ca_file
                else _ssl.create_default_context()
            )

    # -- transport ---------------------------------------------------------

    def _conn(self, channel: str = "main"):
        """Thread-local persistent connection (HTTP/1.1 keep-alive), one per
        (thread, channel).

        urllib opens a fresh TCP (+TLS handshake) connection per request; a
        reconcile makes ~8 wire calls and a 50-job burst makes hundreds —
        per-request handshakes alone put the wire deployment several times
        over the in-process control-plane latency. One keep-alive connection
        per thread brings a call back to ~one round trip, which is the
        wire_overhead bench's whole budget.

        `channel` exists because requests on one connection are strictly
        sequential: the watch long-poll BLOCKS its connection for up to the
        poll timeout, and CRUD calls queued behind it would eat that wait on
        every reconcile. Watch traffic therefore rides its own connection,
        and connections stay warm for the client's lifetime — they are only
        dropped on a transport error (and then rebuilt on the next call).
        """
        conn = getattr(self._local, "conn_" + channel, None)
        if conn is None:
            if self._scheme == "https":
                conn = http.client.HTTPSConnection(
                    self._host, self._port, timeout=self.timeout,
                    context=self._ssl_context,
                )
            else:
                conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self.timeout
                )
            conn.connect()
            # Same delayed-ACK tax in the other direction: the request line/
            # headers and the JSON body are separate send()s too.
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            setattr(self._local, "conn_" + channel, conn)
        return conn

    def _drop_conn(self, channel: str = "main") -> None:
        conn = getattr(self._local, "conn_" + channel, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            setattr(self._local, "conn_" + channel, None)

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        query: Optional[Dict[str, str]] = None,
        channel: str = "main",
        idempotent: bool = True,
    ) -> Any:
        """`idempotent=False` marks a request whose GET is NOT safe to
        replay transparently — the watch-session drain, a DESTRUCTIVE read:
        the server empties the queue when it serves the response, so if the
        response is lost on a stale keep-alive connection, a silent retry
        returns a fresh (empty) drain and the lost events are gone forever.
        Such calls surface ApiUnavailableError instead and the caller heals
        by relist."""
        target = path
        if query:
            target += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        headers = self._headers

        for attempt in (0, 1):
            try:
                # Inside the try: _conn() performs the TCP connect AND the
                # TLS handshake, where cert verification failures surface.
                conn = self._conn(channel)
                conn.request(method, target, body=data, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                status = resp.status
                break
            except (http.client.HTTPException, socket.timeout, OSError) as e:
                self._drop_conn(channel)
                if isinstance(e, _ssl.SSLCertVerificationError):
                    # A server cert the pinned CA didn't sign is a
                    # configuration (or impersonation) problem — retrying
                    # forever in the operator loop would just mask it.
                    raise PermissionError(
                        f"{method} {path}: TLS verification failed: {e}"
                    ) from None
                if attempt == 0 and method == "GET" and idempotent and isinstance(
                    e,
                    (
                        http.client.RemoteDisconnected,
                        http.client.BadStatusLine,
                        ConnectionResetError,
                        BrokenPipeError,
                    ),
                ):
                    # A stale keep-alive connection the server closed while
                    # we were idle dies exactly this way on the next use;
                    # one transparent retry on a FRESH connection is standard
                    # (urllib3 does the same) — but only for an IDEMPOTENT
                    # GET: replaying a POST whose response was lost could
                    # double-apply a create/log-append server-side, and
                    # replaying a watch drain (a destructive read) would
                    # silently drop the events the lost response carried.
                    # Non-idempotent calls surface ApiUnavailableError and
                    # the caller's retry arm (reconcile requeue, watch
                    # relist) absorbs it.
                    continue
                raise ApiUnavailableError(f"{method} {path}: {e}") from None

        if status < 400:
            return json.loads(raw or b"{}")
        try:
            payload = json.loads(raw or b"{}")
        except ValueError:
            payload = {}
        kind = payload.get("error", "")
        msg = payload.get("message", f"HTTP {status}")
        if status == 404:
            raise NotFoundError(msg)
        if status == 409 and kind == "AlreadyExists":
            raise AlreadyExistsError(msg)
        if status == 409:
            raise ConflictError(msg)
        if status == 422:
            raise ValueError(msg)
        if status == 401:
            # Auth failures are config errors, not transients — the
            # operator loop must NOT retry these silently forever.
            raise PermissionError(msg)
        raise ApiServerError(f"{method} {path}: {status} {msg}")

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        out = wire.decode(self._request("POST", "/objects", body=wire.encode(obj)))
        # The caller's object carries the assigned uid/resourceVersion after
        # create (in-process contract), but the RETURNED object is the
        # server's stored state — including server-side admission mutations
        # (defaulting) the local copy never saw.
        obj.metadata.uid = out.metadata.uid
        obj.metadata.resource_version = out.metadata.resource_version
        return out

    def get(self, kind: str, namespace: str, name: str) -> Any:
        return wire.decode(
            self._request("GET", f"/objects/{_quote_seg(kind)}/{_ns_seg(namespace)}/{_quote_seg(name)}")
        )

    def try_get(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.get(kind, namespace, name)
        except NotFoundError:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        query: Dict[str, str] = {}
        if namespace is not None:
            query["namespace"] = namespace
        if label_selector:
            query["labelSelector"] = ",".join(f"{k}={v}" for k, v in label_selector.items())
        payload = self._request("GET", f"/objects/{_quote_seg(kind)}", query=query or None)
        return [wire.decode(d) for d in payload["items"]]

    def update(self, obj: Any, check_version: bool = True, status_only: bool = False) -> Any:
        ns = getattr(obj.metadata, "namespace", "") or ""
        out = wire.decode(
            self._request(
                "PUT",
                f"/objects/{_quote_seg(obj.KIND)}/{_ns_seg(ns)}/{_quote_seg(obj.metadata.name)}",
                body=wire.encode(obj),
                query={
                    "check_version": "1" if check_version else "0",
                    "status_only": "1" if status_only else "0",
                },
            )
        )
        obj.metadata.resource_version = out.metadata.resource_version
        return out

    def delete(self, kind: str, namespace: str, name: str) -> Any:
        return wire.decode(
            self._request("DELETE", f"/objects/{_quote_seg(kind)}/{_ns_seg(namespace)}/{_quote_seg(name)}")
        )

    def try_delete(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        try:
            return self.delete(kind, namespace, name)
        except NotFoundError:
            return None

    def resource_version(self, kind: str, namespace: str, name: str) -> Optional[int]:
        return self._request("GET", f"/version/{_quote_seg(kind)}/{_ns_seg(namespace)}/{_quote_seg(name)}")[
            "resourceVersion"
        ]

    def server_time(self) -> float:
        """The serving host's cluster-clock reading (GET /time)."""
        return float(self._request("GET", "/time")["now"])

    def metrics_snapshot(self) -> Dict[str, float]:
        """The SERVING process's metrics registry as a flat JSON dict
        (GET /metrics) — how benchmarks and tests verify the wire-cache
        hit-rate claims against the host instead of a self-run."""
        return self._request("GET", "/metrics")

    # -- watch -------------------------------------------------------------

    def watch(self, kinds: Optional[List[str]] = None) -> RemoteWatchQueue:
        if self._shared_watch is None:
            self._shared_watch = _SharedWatch(self)
        return self._shared_watch.subscribe(list(kinds) if kinds else None)

    def unwatch(self, queue: RemoteWatchQueue) -> None:
        if self._shared_watch is not None:
            self._shared_watch.unsubscribe(queue)

    # -- admission ---------------------------------------------------------

    def register_admission(self, kind: str, fn: Callable[[Any], None]) -> None:
        pass  # server-side concern (see class docstring)

    def unregister_admission(self, kind: str, fn: Callable[[Any], None]) -> None:
        pass

    # -- logs / events -----------------------------------------------------

    def append_pod_log(self, namespace: str, name: str, line: str, ts: float = 0.0) -> None:
        self._request(
            "POST", f"/logs/{_ns_seg(namespace)}/{_quote_seg(name)}", body={"line": line, "ts": ts}
        )

    def read_pod_log(
        self, namespace: str, name: str, since: int = 0, tail: Optional[int] = None
    ) -> Tuple[List[str], int]:
        query = {"since": str(since)}
        if tail is not None:
            query["tail"] = str(tail)
        payload = self._request("GET", f"/logs/{_ns_seg(namespace)}/{_quote_seg(name)}", query=query)
        return payload["lines"], payload["cursor"]

    def record_event(self, event: Event) -> None:
        self._request("POST", "/events", body=wire.encode(event))

    def events(
        self, object_name: Optional[str] = None, reason: Optional[str] = None
    ) -> List[Event]:
        query: Dict[str, str] = {}
        if object_name:
            query["object_name"] = object_name
        if reason:
            query["reason"] = reason
        payload = self._request("GET", "/events", query=query or None)
        return [wire.decode(d, Event) for d in payload["items"]]


class CachedReadAPI:
    """RemoteAPIServer proxy serving LIST from a watch-fed mirror.

    The reference's controllers never list from the apiserver on the hot
    path — they read the shared informer's cache and only WRITE direct
    (client-go listers). Without this, every reconcile pays 2+ wire RTTs
    for pod/service lists, and a 200-job burst's operator loop spends most
    of its wall time in serialized round trips (the wire_overhead bench
    measured ~3x the in-process p50; with cached lists it is the write
    traffic that remains).

    Correctness rests on two invariants:

    1. The mirror rides the SAME shared wire session as the manager's event
       queue, and events are distributed to all fanout queues atomically
       under the shared lock. The manager observes a pod create-echo (and
       satisfies expectations) strictly no earlier than the mirror learns
       the same pod — so an expectations-gated reconcile can never see a
       cached list that is behind its own expectation state.
    2. Only list() is cached. get/try_get stay direct: the optimistic-
       concurrency write path (read fresh, mutate, update, retry on
       conflict) must see the CURRENT resourceVersion, or a conflict retry
       loop could spin against its own stale cache.

    Reads return deep copies (the APIServer copy-on-read contract);
    everything else delegates. Use from the single-threaded operator loop
    whose manager tick pumps the shared session; a client with no pumping
    consumer would read an ever-staler mirror.
    """

    def __init__(self, remote: RemoteAPIServer):
        import copy as _copylib

        self._remote = remote
        self._copy = _copylib.deepcopy
        self._mirror: Dict[str, Dict[Tuple[str, str], Any]] = {}
        self._primed: set = set()
        self._q = remote.watch()  # all kinds
        self._q.reset_on_relist = True
        self._q.overflow_limit = 8192  # standby-safe: see RemoteWatchQueue
        # Parallel reconcile workers (OperatorManager parallel_reconciles)
        # list concurrently; mirror mutation must be atomic.
        self._cache_lock = threading.Lock()

    # -- cached reads ------------------------------------------------------

    def _sync_locked(self) -> None:
        for ev in self._q.poll_local():
            if ev is RELIST_RESET:
                # Post-reconnect relist: the events that follow are the
                # COMPLETE state. Dropping the mirror here is what expires
                # objects deleted while the session was down — their
                # Deleted events are gone and will never arrive. Every
                # registry kind is re-listed, so mark them all primed (a
                # kind with zero objects is correctly represented by an
                # empty bucket, not by a re-prime).
                self._mirror.clear()
                self._primed = set(wire.KIND_REGISTRY)
                continue
            if ev is QUEUE_OVERFLOW:
                # The queue overflowed while nobody was listing (a standby
                # term): the event history is gone, so the mirror cannot be
                # patched — rebuild lazily from authoritative lists.
                self._mirror.clear()
                self._primed.clear()
                continue
            ns = getattr(ev.obj.metadata, "namespace", "") or ""
            key = (ns, ev.obj.metadata.name)
            if ev.type == "Deleted":
                self._mirror.get(ev.kind, {}).pop(key, None)
            else:
                self._mirror.setdefault(ev.kind, {})[key] = ev.obj

    def _prime_locked(self, kind: str) -> None:
        """Initial LIST for a kind (the informer's ListAndWatch seed). The
        watch was opened before priming, so an object created in between
        appears in both — upsert order makes that harmless."""
        bucket = self._mirror.setdefault(kind, {})
        for obj in self._remote.list(kind):
            ns = getattr(obj.metadata, "namespace", "") or ""
            bucket[(ns, obj.metadata.name)] = obj
        self._primed.add(kind)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        with self._cache_lock:
            self._sync_locked()
            if kind not in self._primed:
                self._prime_locked(kind)
            out = []
            for (ns, _), obj in self._mirror.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                if label_selector:
                    labels = obj.metadata.labels
                    if not all(
                        labels.get(k) == v for k, v in label_selector.items()
                    ):
                        continue
                out.append(self._copy(obj))
            return out

    # -- everything else: delegate ----------------------------------------

    def __getattr__(self, name: str) -> Any:
        return getattr(self._remote, name)


# ---------------------------------------------------------------------------
# Operator-side runtime
# ---------------------------------------------------------------------------


class SyncedClock(Clock):
    """A clock slaved to the serving host's cluster clock via GET /time.

    Every timestamp a remote operator writes into shared state — lease
    acquire/renew times above all — must be comparable with timestamps other
    processes write. Per-process `time.monotonic()` epochs are machine-boot-
    relative: two operators on different machines would compare leases
    across incomparable epochs, permanently blocking takeover or causing
    instant split-brain. The reference avoids this by using apiserver-
    comparable wall time for lease renewTime; this clock goes one better
    and slaves directly to the HOST's clock, so even wall-clock skew
    between machines cancels out.

    now() = local_monotonic + offset, where offset is estimated against
    /time with a midpoint RTT correction and re-estimated every
    `resync_interval`. Between resyncs the clock advances on the local
    monotonic rate (no network call per now()); a failed resync keeps the
    previous offset — a host outage must not stop operator-local time.
    """

    def __init__(self, remote: "RemoteAPIServer", resync_interval: float = 30.0):
        # Dedicated short-timeout client: the probe runs INSIDE now(), i.e.
        # inside the operator tick loop — inheriting the 30s CRUD timeout
        # would freeze ticks for up to 30s per resync attempt during a
        # blackholed-host partition, exactly when responsiveness matters.
        self._probe = RemoteAPIServer(
            remote.base_url, timeout=2.0, token=remote.token,
            ca_file=remote.ca_file,
        )
        self._resync_interval = resync_interval
        self._offset: Optional[float] = None
        self._last_sync = -float("inf")
        self._sync()

    def _sync(self) -> None:
        t0 = _time.monotonic()
        try:
            server_now = self._probe.server_time()
        except (ApiUnavailableError, ApiServerError, PermissionError):
            # Count the ATTEMPT as the last sync: during a host outage,
            # now() must keep running on the cached offset at local rate —
            # one failed probe per resync_interval, not a blocking network
            # call per now() (which would freeze the operator tick loop for
            # the socket timeout, per call, exactly when responsiveness to
            # the host's return matters most).
            self._last_sync = _time.monotonic()
            if self._offset is None:
                # Never synced: fall back to wall time so timestamps are at
                # least cross-machine *meaningful*; a later successful
                # resync snaps onto the host epoch.
                self._offset = _time.time() - t0
            return
        t1 = _time.monotonic()
        self._offset = server_now - (t0 + t1) / 2.0
        self._last_sync = t1

    def now(self) -> float:
        local = _time.monotonic()
        if local - self._last_sync > self._resync_interval:
            self._sync()
            local = _time.monotonic()
        return local + self._offset


class RemoteRuntime:
    """Run loop for a process whose API server lives elsewhere.

    Shape-compatible with `Cluster` for everything the operator stack and
    the SDK consume (`api`, `clock`, `add_ticker`/`remove_ticker`,
    `schedule_at`/`schedule_after`, `run_until`/`run_for`, `live`), but with
    no local store, scheduler, or kubelet — those live in the serving
    process. Always real-clock: across OS processes there is no shared
    virtual time.
    """

    def __init__(self, api: RemoteAPIServer, tick_interval: float = 0.02):
        self.api = api
        # Host-slaved time (see SyncedClock): lease and TTL arithmetic in
        # this process compares against timestamps other processes wrote.
        self.clock = SyncedClock(api)
        self.tick_interval = tick_interval
        self._tickers: List[Callable[[], None]] = []
        self._timers: List[Tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = itertools.count()
        # schedule_after is called from reconcile WORKER threads (requeue
        # backoff) while the main loop pops due timers in step(); heapq on
        # a shared list is not thread-safe, and a corrupted heap silently
        # delays or drops requeue timers. All heap mutation goes through
        # this lock; timer callbacks run OUTSIDE it (a callback that
        # schedules again must not deadlock).
        self._timers_lock = threading.Lock()

    def add_ticker(self, fn: Callable[[], None]) -> None:
        self._tickers.append(fn)

    def remove_ticker(self, fn: Callable[[], None]) -> None:
        try:
            self._tickers.remove(fn)
        except ValueError:
            pass

    def schedule_at(self, t: float, fn: Callable[[], None]) -> None:
        with self._timers_lock:
            heapq.heappush(self._timers, (t, next(self._timer_seq), fn))

    def schedule_after(self, dt: float, fn: Callable[[], None]) -> None:
        self.schedule_at(self.clock.now() + dt, fn)

    def live(self, obj: Any) -> Any:
        ns = getattr(obj.metadata, "namespace", "") or ""
        return self.api.try_get(obj.KIND, ns, obj.metadata.name)

    def step(self) -> None:
        now = self.clock.now()
        while True:
            with self._timers_lock:
                if not self._timers or self._timers[0][0] > now:
                    break
                _, _, fn = heapq.heappop(self._timers)
            fn()
        for fn in list(self._tickers):
            fn()

    def run_until(self, predicate: Callable[[], bool], timeout: float = 30.0) -> bool:
        deadline = self.clock.now() + timeout
        while True:
            if predicate():
                return True
            self.step()
            if predicate():
                return True
            if self.clock.now() >= deadline:
                return False
            _time.sleep(self.tick_interval)

    def run_for(self, seconds: float) -> None:
        self.run_until(lambda: False, timeout=seconds)

    def run_forever(self, stop: threading.Event) -> None:
        """Operator main loop: a transient transport failure (host restart,
        connection reset) is survived with backoff — the process must NOT
        die, or one API hiccup would take out leader and standby together.
        Leadership safety doesn't depend on this: an unrenewable lease just
        expires and the healthiest candidate re-acquires."""
        backoff = 0.1
        while not stop.is_set():
            try:
                self.step()
                backoff = 0.1
            except (ApiUnavailableError, ApiServerError) as e:
                # Transport down, or the server answered 5xx — equally
                # transient from here (k8s clients retry 500s the same
                # way). Anything else — including plain RuntimeError from
                # local code — is a bug and crashes loudly.
                log.warning("API server error (%s); retrying in %.1fs", e, backoff)
                _time.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            _time.sleep(self.tick_interval)
