"""Client-side watch fanout: the one shared wire session, per-consumer
queues, and the watch-fed lister cache.

One of the four modules carved out of the original `cluster/httpapi.py`:
this one owns the informer semantics of the wire client — one server-side
watch session per `RemoteAPIServer`, events fanned out client-side by kind
filter, relist healing after session loss, and the `CachedReadAPI` mirror
that serves reconcile-path LISTs without wire round trips. The transport
lives in `wire_transport.py`; the server in `wire_server.py`; the operator
run loop in `wire_runtime.py`. `cluster/httpapi.py` remains the public
facade re-exporting all of it.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from training_operator_tpu.cluster import wire
from training_operator_tpu.cluster.apiserver import NotFoundError
from training_operator_tpu.utils.locks import TrackedLock, TrackedRLock
from training_operator_tpu.cluster.wire_transport import (
    ApiServerError,
    ApiUnavailableError,
)

# Sentinel delivered (only to opt-in subscribers) at the head of a relist:
# "everything after this is the FULL current state — drop what you had".
# Without it, a mirror fed by Added/Modified/Deleted events can never learn
# about objects deleted while the watch session was lost: the relist only
# re-announces survivors, so ghosts would live in the cache forever.
RELIST_RESET = object()

# Sentinel left as the sole content of a fanout queue whose consumer stopped
# draining and let it hit its overflow limit: "your event history is gone —
# rebuild from authoritative lists". Only mirror-building consumers opt into
# bounded queues; for them a lost history is recoverable (re-prime), whereas
# silently dropping individual events would leave permanent ghosts.
QUEUE_OVERFLOW = object()


class ShardRelistReset:
    """Shard-scoped RELIST_RESET, delivered by the sharded router's merged
    watch queue (cluster/wire_shards.py) in place of the plain sentinel
    when ONE shard's session relisted. The events that follow (from that
    shard) are that shard's complete state — a mirror must drop only the
    keys that shard owns. Dropping everything would be *correct* but would
    turn one shard's too_old into a fleet-wide cache rebuild, defeating
    per-shard healing. `owns(kind, namespace)` is the router's ownership
    predicate for the originating shard."""

    __slots__ = ("shard", "owns")

    def __init__(self, shard: int, owns):
        self.shard = shard
        self.owns = owns


class RemoteWatchQueue:
    """Fanout handle on the client's ONE shared wire watch session.

    Early rounds gave every consumer its own server-side session; with
    several consumers per process (v1 manager + v2 manager), every idle
    tick serialized multiple empty long-polls — over a second of pure
    blocking per tick, a 12x submit->Running overhead on the wire vs
    in-process. This is the informer fix: one wire session per
    RemoteAPIServer (see _SharedWatch), events fanned out client-side by
    kind filter, and at most ONE blocking long-poll per block interval
    across all consumers. Matches the reference, where any number of
    controllers share one informer's watch connection per resource.

    `drain()` semantics are unchanged for consumers: returns pending
    events, long-polling briefly when idle; after a server-side session
    loss it transparently resubscribes with its ResourceVersion watermark
    and receives the missed DELTA (falling back to a full relist only when
    the server's resume ring was outrun — the informer's "410 too old"
    arm), so lost events can delay work but never wedge it.
    """

    def __init__(self, shared: "_SharedWatch", kinds: Optional[List[str]] = None):
        from collections import deque

        self._shared = shared
        self.kinds = set(kinds) if kinds else None
        # Opt-in: receive RELIST_RESET at the head of a post-reconnect
        # relist. Mirror-building consumers (CachedReadAPI) need it;
        # event-driven consumers (the managers, whose periodic resync
        # re-enqueues work from authoritative lists) do not, and must not
        # have to know about the sentinel.
        self.reset_on_relist = False
        # Bound for consumers that may legitimately stop draining for long
        # stretches (a STANDBY operator never lists, so its lister cache
        # never drains — without a bound every cluster event would
        # accumulate in this deque for the whole standby lifetime). 0 = no
        # bound (tick-driven consumers drain every tick by construction).
        # On overflow the queue is collapsed to QUEUE_OVERFLOW.
        self.overflow_limit = 0
        self._local: "deque" = deque()

    def _append(self, item: Any) -> None:
        if self.overflow_limit and len(self._local) >= self.overflow_limit:
            if self._local and self._local[-1] is QUEUE_OVERFLOW:
                return
            self._local.clear()
            self._local.append(QUEUE_OVERFLOW)
            return
        self._local.append(item)

    @property
    def watch_id(self) -> Optional[str]:
        return self._shared.watch_id

    def drain(self, timeout: Optional[float] = None) -> List[Any]:
        return self._shared.drain_for(self, timeout)

    def poll_local(self) -> List[Any]:
        """Drain ONLY events already distributed to this queue — never hits
        the wire. For piggyback consumers (the lister cache) that ride the
        pumping some other consumer (the manager tick) is already doing."""
        with self._shared._lock:
            out = list(self._local)
            self._local.clear()
            return out

    def __len__(self) -> int:
        return len(self._local)


class _SharedWatch:
    """The one wire watch session a RemoteAPIServer multiplexes.

    The server session subscribes to ALL kinds (client-side filters do the
    narrowing): per-subscriber server sessions would resurrect the
    serialized-empty-poll problem this class exists to kill, and the
    operator-side consumers want all kinds anyway.

    Blocking policy: a drain may long-poll the wire only if no blocking
    poll happened within `min_block_interval` (one tick); otherwise an
    empty local queue returns [] immediately. Net effect: an idle process
    holds ONE cheap long-poll open per window (the server parks it on the
    store's condition variable — zero CPU both sides), and event delivery
    latency stays ~one RTT because the parked poll wakes on the write.
    """

    def __init__(
        self,
        remote,
        poll_timeout: float = 0.25,
        min_block_interval: float = 0.02,
        resume: bool = True,
    ):
        self._remote = remote
        self.poll_timeout = poll_timeout
        self.min_block_interval = min_block_interval
        # Present per-kind watermarks on resubscribe so the server replays
        # only the delta; False pins the pre-resume behavior (every
        # reconnect heals by full relist) — the bench's forced-relist
        # comparison leg and the escape hatch against an old host.
        self.resume = resume
        self.watch_id: Optional[str] = None
        self._subs: List[RemoteWatchQueue] = []
        self._needs_relist = False
        self._last_block = -float("inf")
        # Per-kind ResourceVersion watermark: the max WatchEvent.seq this
        # client has DISTRIBUTED (i.e. its consumers have observed), per
        # kind. Survives session reaps by construction — it lives here, not
        # in the server session — which is what makes reconnect O(delta).
        self._watermarks: Dict[str, int] = {}
        # Ring epoch + session-base seq from the server's subscribe
        # response: watermarks are only meaningful against the same server
        # incarnation, and `base` covers kinds with no observed events yet
        # (their knowledge came from post-subscribe LIST primes).
        self._epoch: Optional[str] = None
        self._base = 0
        self._lock = TrackedRLock("wire_watch.session")

    # -- subscriber management --------------------------------------------

    def subscribe(self, kinds: Optional[List[str]]) -> RemoteWatchQueue:
        with self._lock:
            q = RemoteWatchQueue(self, kinds)
            self._subs.append(q)
            if self.watch_id is None:
                self._open()
            return q

    def _session_channel(self) -> str:
        """The channel session-lifecycle requests (open/delete) ride. MUST
        resolve to the same address as the poll channel ("watch"): with
        follower reads on, a session minted on the primary but polled on
        the standby would 404 every poll and turn the whole watch path
        into a permanent heal-and-relist loop that leaks a session on the
        primary per drain. The standby serves /watches by design (its
        resume ring runs in seq lockstep), so the whole session lives
        wherever reads are routed."""
        fn = getattr(self._remote, "_read_channel", None)
        return fn() if fn is not None else "main"

    def unsubscribe(self, q: RemoteWatchQueue) -> None:
        with self._lock:
            if q in self._subs:
                self._subs.remove(q)
            if not self._subs and self.watch_id is not None:
                wid, self.watch_id = self.watch_id, None
                try:
                    self._remote._request("DELETE", f"/watches/{wid}",
                                          channel=self._session_channel())
                except (NotFoundError, ApiUnavailableError, ApiServerError,
                        PermissionError):
                    pass  # server GC reaps stale sessions anyway

    def _open(self, resume: bool = False) -> Dict[str, Any]:
        body: Dict[str, Any] = {"kinds": None}
        if resume and self.resume and self._epoch is not None:
            body["resume"] = dict(self._watermarks)
            body["epoch"] = self._epoch
            body["base"] = self._base
        payload = self._remote._request("POST", "/watches", body=body,
                                        channel=self._session_channel())
        self.watch_id = payload["watch_id"]
        epoch = payload.get("epoch")
        if epoch != self._epoch:
            # First open, or a NEW server incarnation (host restart): seq
            # counters restarted, so the old watermarks are meaningless —
            # and must not be allowed to dedup-drop the new epoch's events.
            self._epoch = epoch
            self._base = int(payload.get("seq", 0) or 0)
            self._watermarks.clear()
        return payload

    # -- pumping ----------------------------------------------------------

    def drain_for(self, q: RemoteWatchQueue, timeout: Optional[float]) -> List[Any]:
        with self._lock:
            if q not in self._subs:
                # Drained after unwatch (or a fresh consumer of a dead
                # handle): rejoin, and heal the unobserved gap by watermark
                # resume (full relist only when the ring was outrun).
                self._subs.append(q)
                self._needs_relist = True
            if not q._local:
                # Contract: an EXPLICIT timeout is an explicit fetch — it
                # always hits the wire. A bare drain() (the tick-loop form)
                # is subject to the block window: if some consumer blocked
                # within the last interval, pending events were already
                # distributed and the next tick's pump is <=interval away.
                if self._needs_relist:
                    self._pump(0.0)
                elif timeout is not None:
                    self._pump(timeout)
                elif (
                    _time.monotonic() - self._last_block
                    >= self.min_block_interval
                ):
                    self._pump(self.poll_timeout)
            out = list(q._local)
            q._local.clear()
            return out

    def _pump(self, t: float) -> None:
        if self.watch_id is None or self._needs_relist:
            # Dead handle (rejoin after unwatch), a lost drain response, or
            # an earlier heal that couldn't finish: close the gap before
            # polling again.
            self._heal()
            return
        if t > 0:
            # Count the attempt, success or not: a 5xx storm must not turn
            # every consumer's drain back into a serial blocking poll.
            self._last_block = _time.monotonic()
        try:
            payload = self._remote._request(
                "GET", f"/watches/{self.watch_id}", query={"timeout": str(t)},
                channel="watch", idempotent=False,
            )
        except ApiUnavailableError:
            # The drain died mid-flight on a transport failure. The server
            # may already have emptied the queue into the lost response —
            # those events are unrecoverable via the SESSION, but they are
            # still in the server's resume ring: mark the gap now, heal on
            # the next drain by watermark resume (relist only if the ring
            # was outrun). A transparent GET retry here (the pre-fix
            # behavior) would return an empty drain and silently drop them.
            self._needs_relist = True
            raise
        except NotFoundError:
            # Session reaped server-side (idle past session_ttl, host
            # restart, injected chaos). Heal immediately: resubscribe
            # presenting the watermarks; the server replays the delta, or
            # answers too-old and the relist arm runs. The server just
            # 404'd this session, so the heal skips the courtesy DELETE.
            self._heal(session_known_dead=True)
            return
        for d in payload["events"]:
            self._distribute(wire.decode_watch_event(d))

    def _heal(self, session_known_dead: bool = False) -> None:
        """Close an observation gap (reaped session, lost drain response,
        rejoined handle): open a FRESH session presenting the per-kind
        watermarks. A "delta" answer replays exactly the missed events —
        O(gap), the informer resume contract — and anything else (ring
        outrun → too_old, resume disabled, an old or restarted host) falls
        back to the existing full-relist arm. The flag is cleared only when
        one of the two heals fully succeeds; a failure mid-heal retries on
        the next drain."""
        self._needs_relist = True
        old, self.watch_id = self.watch_id, None
        if old is not None and not session_known_dead:
            # The abandoned (but possibly still-live) session would only be
            # GC'd at session_ttl; delete best-effort so its queue stops
            # accumulating now. Skipped when the server already 404'd it —
            # that DELETE would be a guaranteed-wasted round trip on the
            # reconnect path the bench measures.
            try:
                self._remote._request("DELETE", f"/watches/{old}",
                                      channel=self._session_channel())
            except (NotFoundError, ApiUnavailableError, ApiServerError,
                    PermissionError):
                pass
        payload = self._open(resume=True)
        if payload.get("resume") == "delta":
            for d in payload.get("events", []):
                self._distribute(wire.decode_watch_event(d))
            self._needs_relist = False
            return
        self._relist()
        # The relist succeeded (a raise above leaves the flag set and the
        # OLD watermarks in place for the retry): the client's knowledge is
        # now complete as of the session open. REBASE the watermark state —
        # without this, one too-old event would poison every later
        # reconnect: quiet kinds keep their outrun watermark forever, so
        # each reap would cascade into another O(cluster) relist.
        self._base = int(payload.get("seq", 0) or 0)
        self._watermarks.clear()

    def _relist(self) -> List[Any]:
        """Synthesize Added events for the full current state. Watch is
        (re)opened BEFORE the lists, so an object written in between can be
        seen twice (consumers are idempotent; expectations tolerate
        over-observation) but never lost. Only a FULLY successful relist
        clears the flag — a 5xx mid-relist retries on the next drain.

        The lists ride pagination (pages of the client's list_page_limit)
        when configured: the too-old arm is exactly where a 10k-object
        cluster would otherwise force the server to materialize one giant
        LIST body per watched kind. Pages served are counted server-side
        in training_wire_list_pages_total."""
        from training_operator_tpu.cluster.apiserver import WatchEvent

        page = getattr(self._remote, "list_page_limit", 0) or None
        events = []
        for kind in wire.KIND_REGISTRY:
            for obj in self._remote.list(kind, limit=page):
                events.append(WatchEvent("Added", kind, obj))
        self._needs_relist = False  # only cleared on a FULLY successful relist
        # Opt-in subscribers (mirror builders) get the reset marker FIRST:
        # what follows is the complete state, and anything they hold that
        # is absent from it was deleted while the session was down — its
        # Deleted event is gone forever.
        for q in self._subs:
            if q.reset_on_relist:
                q._append(RELIST_RESET)
        for ev in events:
            self._distribute(ev)
        return events

    def _distribute(self, ev: Any) -> None:
        # Exactly-once by watermark: the server subscribes the new session
        # BEFORE computing a resume delta, so an event written in that
        # window arrives twice (once replayed, once via the session). The
        # seq dedup drops the second copy — replayed deltas are never
        # double-applied by any consumer (above all the lister cache).
        # Relist-synthesized events carry seq 0 and bypass this (consumers
        # are idempotent under relist over-observation, as before).
        if ev.seq:
            if ev.seq <= self._watermarks.get(ev.kind, 0):
                return
            self._watermarks[ev.kind] = ev.seq
        # One shared decoded copy per event, same as the in-process
        # informer contract (apiserver.py module docstring).
        for q in self._subs:
            if q.kinds is None or ev.kind in q.kinds:
                q._append(ev)


class CachedReadAPI:
    """RemoteAPIServer proxy serving LIST from a watch-fed mirror.

    The reference's controllers never list from the apiserver on the hot
    path — they read the shared informer's cache and only WRITE direct
    (client-go listers). Without this, every reconcile pays 2+ wire RTTs
    for pod/service lists, and a 200-job burst's operator loop spends most
    of its wall time in serialized round trips (the wire_overhead bench
    measured ~3x the in-process p50; with cached lists it is the write
    traffic that remains).

    Correctness rests on two invariants:

    1. The mirror rides the SAME shared wire session as the manager's event
       queue, and events are distributed to all fanout queues atomically
       under the shared lock. The manager observes a pod create-echo (and
       satisfies expectations) strictly no earlier than the mirror learns
       the same pod — so an expectations-gated reconcile can never see a
       cached list that is behind its own expectation state.
    2. Only list() is cached. get/try_get stay direct: the optimistic-
       concurrency write path (read fresh, mutate, update, retry on
       conflict) must see the CURRENT resourceVersion, or a conflict retry
       loop could spin against its own stale cache.

    Reads return deep copies (the APIServer copy-on-read contract);
    everything else delegates. Use from the single-threaded operator loop
    whose manager tick pumps the shared session; a client with no pumping
    consumer would read an ever-staler mirror.
    """

    def __init__(self, remote):
        import copy as _copylib

        self._remote = remote
        self._copy = _copylib.deepcopy
        self._mirror: Dict[str, Dict[Tuple[str, str], Any]] = {}
        self._primed: set = set()
        self._q = remote.watch()  # all kinds
        self._q.reset_on_relist = True
        self._q.overflow_limit = 8192  # standby-safe: see RemoteWatchQueue
        # Parallel reconcile workers (OperatorManager parallel_reconciles)
        # list concurrently; mirror mutation must be atomic.
        self._cache_lock = TrackedLock("wire_watch.cache")

    # -- cached reads ------------------------------------------------------

    def _sync_locked(self) -> None:
        for ev in self._q.poll_local():
            if ev is RELIST_RESET:
                # Post-reconnect relist: the events that follow are the
                # COMPLETE state. Dropping the mirror here is what expires
                # objects deleted while the session was down — their
                # Deleted events are gone and will never arrive. Every
                # registry kind is re-listed, so mark them all primed (a
                # kind with zero objects is correctly represented by an
                # empty bucket, not by a re-prime).
                self._mirror.clear()
                self._primed = set(wire.KIND_REGISTRY)
                continue
            if ev is QUEUE_OVERFLOW:
                # The queue overflowed while nobody was listing (a standby
                # term): the event history is gone, so the mirror cannot be
                # patched — rebuild lazily from authoritative lists.
                self._mirror.clear()
                self._primed.clear()
                continue
            if isinstance(ev, ShardRelistReset):
                # One shard of a sharded router relisted: only that shard's
                # keys are ghosts-at-risk; the other shards' sessions never
                # dropped, so their mirror entries stay live deltas.
                # `_primed` is untouched — the shard relist re-announces
                # only its own objects, which upsert into existing buckets.
                for kind, bucket in self._mirror.items():
                    for key in [k for k in bucket if ev.owns(kind, k[0])]:
                        bucket.pop(key, None)
                continue
            ns = getattr(ev.obj.metadata, "namespace", "") or ""
            key = (ns, ev.obj.metadata.name)
            if ev.type == "Deleted":
                self._mirror.get(ev.kind, {}).pop(key, None)
            else:
                self._mirror.setdefault(ev.kind, {})[key] = ev.obj

    def _prime_locked(self, kind: str) -> None:
        """Initial LIST for a kind (the informer's ListAndWatch seed). The
        watch was opened before priming, so an object created in between
        appears in both — upsert order makes that harmless. Paginated like
        the relist arm when the client configures a page limit."""
        bucket = self._mirror.setdefault(kind, {})
        page = getattr(self._remote, "list_page_limit", 0) or None
        for obj in self._remote.list(kind, limit=page):
            ns = getattr(obj.metadata, "namespace", "") or ""
            bucket[(ns, obj.metadata.name)] = obj
        self._primed.add(kind)

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Any]:
        with self._cache_lock:
            self._sync_locked()
            if kind not in self._primed:
                self._prime_locked(kind)
            out = []
            for (ns, _), obj in self._mirror.get(kind, {}).items():
                if namespace is not None and ns != namespace:
                    continue
                if label_selector:
                    labels = obj.metadata.labels
                    if not all(
                        labels.get(k) == v for k, v in label_selector.items()
                    ):
                        continue
                out.append(self._copy(obj))
            return out

    def try_get_cached(self, kind: str, namespace: str, name: str) -> Optional[Any]:
        """One object from the watch-fed mirror (deep copy; None when
        absent) — the lister-read the reference's reconcilers use for the
        JOB itself, not just its dependents. Explicitly a SEPARATE verb
        from try_get, which stays a direct wire read: lease arbitration and
        the optimistic-concurrency conflict arm need the CURRENT stored
        version, but a reconcile triggered BY a watch event reading the
        event's own object is exactly as fresh from the mirror (events are
        distributed to the manager queue and the mirror atomically), and a
        stale read here costs one resolvable status conflict, never a spin.
        """
        with self._cache_lock:
            self._sync_locked()
            if kind not in self._primed:
                self._prime_locked(kind)
            obj = self._mirror.get(kind, {}).get((namespace or "", name))
            return self._copy(obj) if obj is not None else None

    # -- everything else: delegate ----------------------------------------

    def __getattr__(self, name: str) -> Any:
        return getattr(self._remote, name)
