"""TrainingClient: the user-facing API.

Parity map (reference sdk/python/kubeflow/training/api/training_client.py):
  create_job (:428)            -> create_job
  get_job (:640) / list_jobs (:744) / delete_job (:1440) / update_job (:584)
  wait_for_job_conditions (:888) -> wait_for_job_conditions
  get_job_conditions (:800)    -> get_job_conditions
  is_job_running/succeeded/... (:846-886) -> same names
  get_job_pod_names (:1060)    -> get_job_pod_names
  get_job_logs (:1130)         -> get_job_logs (virtual substrate: the event
                                  stream stands in for container stdout)
  train (:95)                  -> train — TPU-native: submits a v2 TrainJob
                                  wired to a TrainingRuntime with dataset /
                                  model initializers, instead of assembling
                                  a PyTorchJob + PVC by hand.

The client talks to an in-process cluster (tests, simulation, benches) the
way the reference's client talks to a kube-apiserver.
"""

from __future__ import annotations

import time as _time
from typing import Any, Dict, List, Optional, Sequence, Union

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import JobConditionType
from training_operator_tpu.api.jobs import JOB_KINDS, Job
from training_operator_tpu.cluster.apiserver import AlreadyExistsError, NotFoundError
from training_operator_tpu.cluster.runtime import Cluster
from training_operator_tpu.runtime.api import (
    DatasetConfig,
    ModelConfig,
    RuntimeRef,
    Trainer,
    TrainJob,
)
from training_operator_tpu.api.jobs import ObjectMeta

JOB_KIND_NAMES = tuple(JOB_KINDS) + ("TrainJob",)


class TimeoutException(Exception):
    pass


class TrainingClient:
    def __init__(
        self,
        cluster: Union[Cluster, str],
        namespace: str = "default",
        job_kind: str = "JAXJob",
        api_token: Optional[str] = None,
        ca_file: Optional[str] = None,
    ):
        """`cluster` is either an in-process Cluster or a base URL string
        ("https://127.0.0.1:8443") of a serving host process — the remote
        mode mirroring the reference client's REST relationship with the
        kube-apiserver (training_client.py:41). `api_token` is the bearer
        token for a token-gated host; `ca_file` pins the host's CA for an
        https URL (the host announces it as WIRE_CA=...). Remote mode only."""
        if isinstance(cluster, str):
            from training_operator_tpu.cluster.httpapi import (
                RemoteAPIServer,
                RemoteRuntime,
            )

            cluster = RemoteRuntime(
                RemoteAPIServer(cluster, token=api_token, ca_file=ca_file)
            )
        self.cluster = cluster
        self.api = cluster.api
        self.namespace = namespace
        self.job_kind = job_kind
        # (ns, name) -> [kind]: which kind a job turned out to be, so
        # repeated filtered pod lookups don't re-probe every kind.
        self._kind_memo: Dict[Any, List[str]] = {}

    # -- CRUD --------------------------------------------------------------

    def create_job(
        self,
        job: Union[Job, TrainJob],
        namespace: Optional[str] = None,
    ) -> Union[Job, TrainJob]:
        """Admission (defaulting + validation) happens server-side, exactly
        as the reference's create_namespaced_custom_object path does."""
        if namespace:
            job.metadata.namespace = namespace
        elif not job.metadata.namespace:
            job.metadata.namespace = self.namespace
        if isinstance(job, TrainJob):
            if job.metadata.creation_time is None:
                job.metadata.creation_time = self.cluster.clock.now()
        else:
            from training_operator_tpu.api.defaults import default_job

            default_job(job, now=self.cluster.clock.now())
        return self._create_with_retry(job)

    def _create_with_retry(self, job, attempts: int = 5):
        """Remote-mode resilience (no-op in-process: these exception types
        never fire there). A create can hit a transient transport failure —
        above all the stale-keep-alive window right after a HOST RESTART,
        where the pooled connection targets the dead incarnation's socket.
        The wire client deliberately does NOT auto-retry non-idempotent
        calls (the request may have landed); the SDK is the right layer to
        resolve the ambiguity, the way kube clients do: retry, and treat
        AlreadyExists on a RETRY as our own earlier attempt having landed
        (returning the stored object)."""
        import time as _t

        from training_operator_tpu.cluster.httpapi import (
            ApiServerError,
            ApiUnavailableError,
        )

        delay = 0.2
        for attempt in range(attempts):
            try:
                return self.api.create(job)
            except (ApiUnavailableError, ApiServerError):
                if attempt == attempts - 1:
                    raise
                _t.sleep(delay)
                delay = min(delay * 2, 2.0)
            except AlreadyExistsError:
                if attempt == 0:
                    raise  # a genuine name conflict, not our retry's echo
                ns = job.metadata.namespace or ""
                return self.api.get(job.KIND, ns, job.metadata.name)

    def get_job(self, name: str, namespace: Optional[str] = None,
                job_kind: Optional[str] = None):
        return self.api.get(job_kind or self.job_kind, namespace or self.namespace, name)

    def list_jobs(self, namespace: Optional[str] = None,
                  job_kind: Optional[str] = None) -> List[Any]:
        return self.api.list(job_kind or self.job_kind, namespace or self.namespace)

    def update_job(self, job) -> Any:
        return self.api.update(job, check_version=False)

    def delete_job(self, name: str, namespace: Optional[str] = None,
                   job_kind: Optional[str] = None) -> None:
        self.api.delete(job_kind or self.job_kind, namespace or self.namespace, name)

    # -- conditions --------------------------------------------------------

    def get_job_conditions(self, name: str, namespace: Optional[str] = None,
                           job_kind: Optional[str] = None) -> List[Any]:
        job = self.get_job(name, namespace, job_kind)
        return list(job.status.conditions)

    def is_job_created(self, name: str, **kw) -> bool:
        return self._has(name, JobConditionType.CREATED, **kw)

    def is_job_running(self, name: str, **kw) -> bool:
        return self._has(name, JobConditionType.RUNNING, **kw)

    def is_job_restarting(self, name: str, **kw) -> bool:
        return self._has(name, JobConditionType.RESTARTING, **kw)

    def is_job_suspended(self, name: str, **kw) -> bool:
        return self._has(name, JobConditionType.SUSPENDED, **kw)

    def is_job_succeeded(self, name: str, **kw) -> bool:
        return self._has(name, JobConditionType.SUCCEEDED, **kw)

    def is_job_failed(self, name: str, **kw) -> bool:
        return self._has(name, JobConditionType.FAILED, **kw)

    def _has(self, name: str, cond: JobConditionType,
             namespace: Optional[str] = None, job_kind: Optional[str] = None) -> bool:
        job = self.get_job(name, namespace, job_kind)
        c = capi.get_condition(job.status, cond)
        return c is not None and c.status

    def wait_for_job_conditions(
        self,
        name: str,
        namespace: Optional[str] = None,
        job_kind: Optional[str] = None,
        expected_conditions: Sequence[JobConditionType] = (JobConditionType.SUCCEEDED,),
        timeout: float = 600,
        raise_on_failed: bool = True,
    ):
        """Drive the cluster until the job reaches one of the expected
        conditions (reference training_client.py:888 — polling + watch).
        Raises on Failed unless Failed is expected (same contract)."""
        expected = set(expected_conditions)

        def reached() -> bool:
            try:
                job = self.get_job(name, namespace, job_kind)
            except NotFoundError:
                return False
            if raise_on_failed and JobConditionType.FAILED not in expected:
                c = capi.get_condition(job.status, JobConditionType.FAILED)
                if c is not None and c.status:
                    raise RuntimeError(f"job {name} failed: {c.reason}: {c.message}")
            return any(self._cond_true(job, e) for e in expected)

        if self.cluster.run_until(reached, timeout=timeout):
            return self.get_job(name, namespace, job_kind)
        raise TimeoutException(
            f"timeout waiting for {expected} on {job_kind or self.job_kind} {name}"
        )

    @staticmethod
    def _cond_true(job, cond: JobConditionType) -> bool:
        c = capi.get_condition(job.status, cond)
        return c is not None and c.status

    def wait_for_trainjob(
        self,
        name: str,
        namespace: Optional[str] = None,
        timeout: float = 600,
        raise_on_failed: bool = True,
    ) -> TrainJob:
        """Drive the cluster until the v2 TrainJob reaches a terminal
        condition (Complete/Failed); returns the final object. The reference
        v2 SDK is an 18-line stub — this provides the v1 wait ergonomics for
        the v2 kind."""
        from training_operator_tpu.runtime.api import TrainJobConditionType

        ns = namespace or self.namespace

        def reached() -> bool:
            tj = self.api.try_get(TrainJob.KIND, ns, name)
            if tj is None:
                return False
            failed = tj.condition(TrainJobConditionType.FAILED)
            if raise_on_failed and failed is not None and failed.status:
                raise RuntimeError(f"TrainJob {name} failed: {failed.message}")
            return tj.is_finished()

        if self.cluster.run_until(reached, timeout=timeout):
            return self.api.get(TrainJob.KIND, ns, name)
        raise TimeoutException(f"timeout waiting for TrainJob {name} to finish")

    # -- pods / logs -------------------------------------------------------

    def get_job_pods(
        self,
        name: str,
        namespace: Optional[str] = None,
        is_master: bool = False,
        replica_type: Optional[str] = None,
        replica_index: Optional[int] = None,
    ) -> List[Any]:
        """Pod objects for a job, optionally filtered by role / replica type
        / replica index (reference training_client.py:982 get_job_pods with
        its label-selector composition)."""
        ns = namespace or self.namespace
        sel = {capi.JOB_NAME_LABEL: name}
        if is_master:
            sel[capi.JOB_ROLE_LABEL] = "master"
        if replica_type:
            # Labels carry the replica type verbatim ("Worker", "Master" —
            # see engine/core.py replica_labels), unlike the reference's
            # lowercased form. Validate against the job's actual replica
            # types so a typo (or reference-style lowercase "worker")
            # raises like the reference (training_client.py:1028-1053)
            # instead of silently matching nothing. The job's kind is
            # memoized per (ns, name): this runs inside polling loops, and
            # in remote mode each probe is an HTTP round-trip — the
            # client's default kind is tried first.
            cache_key = (ns, name)
            kinds = self._kind_memo.get(cache_key)
            if kinds is None:
                kinds = [self.job_kind] + [
                    k for k in JOB_KIND_NAMES if k != self.job_kind
                ]
            for kind in kinds:
                job = self.api.try_get(kind, ns, name)
                if job is not None and hasattr(job, "replica_specs"):
                    self._kind_memo[cache_key] = [kind]
                    known = sorted(job.replica_specs)
                    if str(replica_type) not in known:
                        raise ValueError(
                            f"replica_type {replica_type!r} not in {kind} "
                            f"{name}'s replica types {known}"
                        )
                    break
            sel[capi.REPLICA_TYPE_LABEL] = str(replica_type)
        if replica_index is not None:
            sel[capi.REPLICA_INDEX_LABEL] = str(replica_index)
        return sorted(self.api.list("Pod", ns, sel), key=lambda p: p.name)

    def get_job_pod_names(self, name: str, namespace: Optional[str] = None,
                          is_master: bool = False) -> List[str]:
        return [p.name for p in self.get_job_pods(name, namespace, is_master)]

    def get_job_logs(
        self,
        name: str,
        namespace: Optional[str] = None,
        tail: Optional[int] = None,
    ) -> Dict[str, str]:
        """Pod name -> that pod's OWN log (kubelet lifecycle lines +
        container stdout; reference training_client.py:1130 read_namespaced_
        pod_log). `tail` limits each pod to its last N lines."""
        ns = namespace or self.namespace
        logs: Dict[str, str] = {}
        for pod in self.api.list("Pod", ns, {capi.JOB_NAME_LABEL: name}):
            lines, _ = self.api.read_pod_log(ns, pod.name, tail=tail)
            logs[pod.name] = "\n".join(lines)
        return logs

    def follow_job_logs(
        self,
        name: str,
        namespace: Optional[str] = None,
        timeout: float = 600.0,
        poll: float = 1.0,
    ):
        """Generator streaming (pod_name, line) as pods emit them — the
        reference's get_job_logs(follow=True). Advances the cluster between
        polls (the in-process analogue of a blocking HTTP log stream) and
        ends when the job is finished and all retained lines are drained."""
        ns = namespace or self.namespace
        # Cursors keyed by pod UID: a pod deleted and recreated under the
        # same deterministic name (elastic TPU resize) gets a fresh log
        # buffer — a name-keyed cursor would skip its first lines.
        cursors: Dict[str, int] = {}
        waited = 0.0
        seen_job = False
        while True:
            job_done = None
            for kind in JOB_KIND_NAMES:
                obj = self.api.try_get(kind, ns, name)
                if obj is not None:
                    status = getattr(obj, "status", None)
                    job_done = (
                        obj.is_finished()
                        if hasattr(obj, "is_finished")
                        else capi.is_finished(status)
                    )
                    break
            if job_done is None and not seen_job:
                # A typo'd name must not read as "finished with no logs" —
                # the other SDK calls raise for the same mistake.
                raise NotFoundError(f"no job named {ns}/{name}")
            seen_job = seen_job or job_done is not None
            for pod in sorted(
                self.api.list("Pod", ns, {capi.JOB_NAME_LABEL: name}),
                key=lambda p: p.name,
            ):
                lines, cursors[pod.metadata.uid] = self.api.read_pod_log(
                    ns, pod.name, since=cursors.get(pod.metadata.uid, 0)
                )
                for line in lines:
                    yield pod.name, line
            if job_done or job_done is None:
                # Finished — or deleted mid-follow (TTL/cascade GC): either
                # way the retained tail above has been drained; end cleanly
                # like the blocking HTTP stream the reference wraps.
                return
            if waited >= timeout:
                raise TimeoutException(f"timeout following logs of {name}")
            self.cluster.run_for(poll)
            waited += poll

    # -- observability -----------------------------------------------------

    def get_job_timeline(
        self, name: str, namespace: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        """The job's lifecycle timeline (admission / queue-wait / gang-solve
        / bind / time-to-running spans) from the API server's ring — the
        wire route GET /timelines/{ns}/{name} in remote mode. None when
        nothing was recorded. Feed to observe.export_chrome_trace for a
        chrome://tracing / Perfetto flame view."""
        return self.api.get_timeline(namespace or self.namespace, name)

    def describe_job(self, name: str, namespace: Optional[str] = None) -> str:
        """kubectl-describe analogue: condition history + Events + phase
        table for one job (see observe/describe.py; also available as
        `python -m training_operator_tpu describe <ns>/<job>`)."""
        from training_operator_tpu.observe import render_describe

        return render_describe(self.api, namespace or self.namespace, name)

    def explain_job(
        self, name: str, namespace: Optional[str] = None
    ) -> Dict[str, Any]:
        """Why is (or was) this job not running: time-to-running decomposed
        into the registered cause taxonomy (observe/attribution.py) — quota
        wait, priority wait, topology fragmentation, preemption
        displacement, node-loss recovery, control-plane overhead, startup.
        Works live (window = creation -> now) and post-mortem. In remote
        mode the report is built server-side (GET /explain/{ns}/{name} —
        through the sharded router it comes from the job's owning shard,
        where all its evidence lives); feed to render_explain() for text.
        CLI twin: `python -m training_operator_tpu explain <ns>/<job>`."""
        ns = namespace or self.namespace
        remote = getattr(self.api, "explain", None)
        if callable(remote):
            return remote(ns, name)
        from training_operator_tpu.observe import explain

        return explain(self.api, ns, name)

    # -- node admin --------------------------------------------------------

    def cordon_node(self, name: str):
        """Mark a node unschedulable (kubectl cordon); running pods stay.
        Works in-process and against a serving host alike (the CLI twin is
        `python -m training_operator_tpu cordon <node> --api-server URL`)."""
        from training_operator_tpu.controllers.nodelifecycle import cordon_node

        return cordon_node(self.api, name, now=self.cluster.clock.now())

    def uncordon_node(self, name: str):
        from training_operator_tpu.controllers.nodelifecycle import uncordon_node

        return uncordon_node(self.api, name, now=self.cluster.clock.now())

    def drain_node(self, name: str) -> List[str]:
        """kubectl drain: cordon + evict every pod on the node (NODE_LOST
        marker — the engine reschedules, gangs re-solve, no restart budget
        burned). Returns the evicted pod names."""
        from training_operator_tpu.controllers.nodelifecycle import drain_node

        return drain_node(self.api, name, now=self.cluster.clock.now())

    # -- tenancy (queues, priority) ----------------------------------------

    def create_priority_class(self, pc):
        """Store a tenancy PriorityClass (tenancy/api.py) — admission
        validates it wherever the store lives (host role or in-process)."""
        return self.api.create(pc)

    def create_cluster_queue(self, cq):
        """Store a tenancy ClusterQueue (per-team quota/borrowing/weight)."""
        return self.api.create(cq)

    def list_priority_classes(self) -> List[Any]:
        return self.api.list("PriorityClass")

    def list_cluster_queues(self) -> List[Any]:
        return self.api.list("ClusterQueue")

    # -- SLO ---------------------------------------------------------------

    def create_slo_policy(self, policy):
        """Store an SLOPolicy (observe/slo.py) — cluster-scoped, admission-
        validated, evaluated by the fleet plane's burn-rate engine."""
        return self.api.create(policy)

    def list_slo_policies(self) -> List[Any]:
        return self.api.list("SLOPolicy")

    def get_slo(self) -> Dict[str, Any]:
        """The current SLO section: per-objective attainment / budget /
        burn rates + per-queue attribution shares. Remote mode fetches the
        host's GET /slo; in-process runs an event-silent evaluation."""
        remote = getattr(self.api, "get_slo", None)
        if callable(remote):
            return remote()
        from training_operator_tpu.observe import SLOEvaluator

        return SLOEvaluator(
            self.api, self.cluster.clock.now, enable_events=False,
        ).evaluate()

    # -- static analysis ---------------------------------------------------

    def lint(self, job: Union[TrainJob, str], namespace: Optional[str] = None):
        """Static dry-run of a TrainJob against the live cluster: the spec
        analyzer (analysis/speclint.py) run with the resolved runtime, the
        cluster's node inventory, and the queued PodGroups — the same pass
        the admission webhook applies, but client-side and fully advisory.
        `job` may be a TrainJob object (not yet created) or the name of an
        existing one. Returns a LintReport."""
        from training_operator_tpu.analysis.speclint import analyze_trainjob
        from training_operator_tpu.runtime.api import (
            ClusterTrainingRuntime,
            TrainingRuntime,
        )

        ns = namespace or self.namespace
        if isinstance(job, str):
            job = self.api.get(TrainJob.KIND, ns, job)
        ref = job.runtime_ref
        if ref.kind == TrainingRuntime.KIND:
            runtime = self.api.try_get(
                TrainingRuntime.KIND, job.metadata.namespace or ns, ref.name
            )
        else:
            runtime = self.api.try_get(ClusterTrainingRuntime.KIND, "", ref.name)
        if runtime is None and ref.kind == ClusterTrainingRuntime.KIND:
            # Pre-install lint (fresh cluster, presets not yet installed):
            # fall back to the built-in catalog the manager would install.
            from training_operator_tpu.runtime.presets import builtin_runtimes

            for rt in builtin_runtimes():
                if rt.metadata.name == ref.name:
                    runtime = rt
                    break
        nodes = self.api.list("Node")
        return analyze_trainjob(
            job,
            runtime,
            nodes=nodes if nodes else None,
            podgroups=self.api.list("PodGroup"),
            target=job.metadata.name,
            priority_classes=self.api.list("PriorityClass"),
            cluster_queues=self.api.list("ClusterQueue"),
        )

    # -- high-level fine-tune ---------------------------------------------

    def train(
        self,
        name: str,
        runtime_ref: str = "tpu-jax-default",
        runtime_kind: str = "ClusterTrainingRuntime",
        namespace: Optional[str] = None,
        model_uri: Optional[str] = None,
        dataset_uri: Optional[str] = None,
        output_uri: Optional[str] = None,
        image: Optional[str] = None,
        args: Optional[List[str]] = None,
        env: Optional[Dict[str, str]] = None,
        num_nodes: Optional[int] = None,
        resources_per_node: Optional[Dict[str, float]] = None,
    ) -> TrainJob:
        """High-level LLM fine-tune entry (reference train(), :95-314):
        one call wires model + dataset initializers and the trainer into a
        declarative TrainJob; the runtime decides topology and bootstrap."""
        job = TrainJob(
            metadata=ObjectMeta(name=name, namespace=namespace or self.namespace),
            runtime_ref=RuntimeRef(name=runtime_ref, kind=runtime_kind),
            trainer=Trainer(
                image=image,
                args=list(args or []),
                env=dict(env or {}),
                num_nodes=num_nodes,
                resources_per_node=dict(resources_per_node or {}),
            ),
            dataset_config=DatasetConfig(storage_uri=dataset_uri) if dataset_uri else None,
            model_config=(
                ModelConfig(input_storage_uri=model_uri, output_storage_uri=output_uri)
                if (model_uri or output_uri) else None
            ),
        )
        return self.create_job(job)
