"""Python client SDK.

Parity target: reference sdk/python/kubeflow/training (TrainingClient at
api/training_client.py:41 — create/get/list/patch/delete any job kind,
wait_for_job_conditions, get_job_logs, and the high-level train() fine-tune
entry at :95-314). The TPU-native train() targets the v2 TrainJob +
TrainingRuntime surface instead of hand-assembling a PyTorchJob.
"""

from training_operator_tpu.sdk.client import TrainingClient

__all__ = ["TrainingClient"]
