"""Operator configuration (reference pkg/config/config.go:36 + the flag
surface of cmd/training-operator.v1/main.go:72-223).

`OperatorConfig` carries everything the process entry point wires: which job
kinds are enabled (the reference's --enable-scheme repeated flag), which gang
scheduler backs PodGroups (--gang-scheduler-name), the namespace scope
(--namespace), reconcile batch width (--controller-threads analogue), solver
cadence, probe/metrics ports, and the default images the reference keeps in
config.Config (e.g. the PyTorch master-wait init container).

A module-level `current()` config replaces the reference's package-global
config.Config; controllers read defaults through it so deployments can
override images without touching controller code.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, fields
from typing import Dict, List, Optional

ALL_SCHEMES = ("jax", "pytorch", "tensorflow", "xgboost", "paddle", "mpi")
GANG_SCHEDULERS = ("none", "tpu-packer", "baseline", "baseline-firstfit")
SOLVER_KERNELS = ("python", "numpy", "jax")
CHAOS_TIERS = ("pod", "api", "wire", "node", "host")


def parse_chaos_intensity(spec: str) -> Dict[str, float]:
    """Parse a per-tier chaos intensity spec ("pod=1,api=0.5,...") into a
    full tier->intensity map; unnamed tiers default to 1.0. Raises
    ValueError on unknown tiers or negative intensities — config.validate
    calls this so a bad spec fails at config time, not mid-soak."""
    out = {tier: 1.0 for tier in CHAOS_TIERS}
    for pair in (spec or "").split(","):
        if not pair.strip():
            continue
        key, _, value = pair.partition("=")
        key = key.strip()
        if key not in CHAOS_TIERS:
            raise ValueError(
                f"unknown chaos tier {key!r} in {spec!r}; "
                f"choose from {CHAOS_TIERS}"
            )
        intensity = float(value)
        if intensity < 0:
            raise ValueError(f"chaos intensity for {key} must be >= 0")
        out[key] = intensity
    return out


@dataclass
class OperatorConfig:
    # Which job kinds get controllers (reference --enable-scheme; empty =
    # all, matching the reference's default of every registered scheme).
    enabled_schemes: List[str] = field(default_factory=lambda: list(ALL_SCHEMES))
    # Gang scheduling backend: "none" disables PodGroup gating entirely;
    # "tpu-packer" is the batched placement engine; "baseline"/"baseline-
    # firstfit" are the comparison placers (reference --gang-scheduler-name,
    # which selects volcano vs scheduler-plugins).
    gang_scheduler_name: str = "tpu-packer"
    # Namespace scope; None/"" watches all namespaces (reference --namespace).
    namespace: Optional[str] = None
    # Reconciles drained per manager tick (reference --controller-threads).
    controller_threads: int = 256
    # Gang solve cadence (GangScheduler knobs).
    resolve_period: float = 15.0
    min_solve_interval: float = 0.0
    # Incremental gang solver (scheduler/gang.py + snapshot.py, PR 10):
    #   solver_incremental — per-group dirty tracking + the long-lived
    #       delta-maintained ClusterSnapshot. A cycle triggered only by
    #       demand-side events re-solves just the dirty gangs; capacity/
    #       tenancy events and the periodic resolve force the full set.
    #       False pins the pre-incremental behavior (global dirty bit +
    #       per-cycle snapshot construction) as the compat arm.
    #   solver_kernel — candidate-scoring kernel: "numpy" (default fast
    #       path, no per-cycle dispatch cost), "jax" (XLA-compiled opt-in,
    #       prewarmed + pow2-padded; run under JAX_PLATFORMS=cpu on the
    #       control plane), "python" (auditable reference arm). All three
    #       return identical placements (property-tested).
    #   snapshot_selfcheck_every — every N solve cycles diff the
    #       incremental snapshot against a cold full-walk rebuild and adopt
    #       the rebuild on mismatch (SnapshotDrift event +
    #       training_solver_snapshot_rebuilds_total). 0 disables.
    solver_incremental: bool = True
    solver_kernel: str = "numpy"
    snapshot_selfcheck_every: int = 0
    # Tail-latency SLO knobs (TPUPacker; see scheduler/packer.py:158-199
    # and the README tail-latency sweep for the measured trade-offs):
    #   drain_reserve_seconds — a whole-slice gang waiting longer than this
    #       triggers drain reservations (nearly-empty slices withheld from
    #       backfill so they drain to fully-free). <=0 disables.
    #   max_drain_fraction — cap on the fraction of slices withheld per
    #       cycle, protecting the median path's capacity.
    #   aging_seconds — a gang waiting longer than this is promoted to the
    #       front in FIFO order, bounding starvation under WSJF.
    # Defaults are the measured 1k-burst sweet spot (300s/0.08: p99 -1.2%,
    # util +0.9pp vs drain-off at unchanged p50); the aggressive corner
    # (150s/0.15) cuts whole-slice p90 ~20% but shifts tail onto sub-slice
    # gangs — a class-fairness choice a deployment makes HERE, not by
    # editing source.
    drain_reserve_seconds: float = 300.0
    max_drain_fraction: float = 0.08
    aging_seconds: float = 300.0
    # Watch-resume ring: events retained PER KIND by the wire API server
    # for ResourceVersion delta resume (httpapi.ApiHTTPServer). A reconnect
    # whose watermark the ring has outrun falls back to a full relist
    # ("410 too old"); size it above the peak event rate times the longest
    # expected reconnect window. The default absorbs a full 1k-job burst's
    # pod events with headroom.
    watch_ring_size: int = 8192
    # Wire protocol v2 (cluster/wire_transport.py; operator role only — the
    # host serves both protocols and standalone mode has no wire at all):
    #   wire_pipeline_depth — max ops framed into one POST /batch envelope
    #       (request pipelining on the persistent channel). 0 pins wire
    #       protocol v1: per-request HTTP, no batching, no coalescing.
    #   coalesce_window_ms — bound on how long a status write may sit in
    #       the client-side last-write-wins buffer before a flush; the
    #       manager also flushes every tick and the engine flushes terminal
    #       writes immediately, so this is the worst case, not the norm.
    #       0 disables coalescing (every update is its own round trip).
    #   list_page_limit — page size for chunked LISTs (limit/continue) on
    #       the full-relist and informer-prime arms, so a 10k-object relist
    #       never materializes one giant body server-side. 0 = unpaginated.
    wire_pipeline_depth: int = 64
    coalesce_window_ms: float = 20.0
    list_page_limit: int = 500
    # Host durability knobs (cluster/store.py HostStore; --state-dir role).
    # Compaction fires when EITHER bound is exceeded: record count (the
    # original knob) or journal BYTES — a few huge objects (big ConfigMaps,
    # 1k-pod snapshots) can grow a journal unboundedly long before 4096
    # records accumulate. 0 disables the bytes trigger.
    compact_every: int = 4096
    compact_max_journal_bytes: int = 64 * 1024 * 1024
    # Per-record durability: False = flush() per record (survives kill -9
    # of the host — the failure mode HA exercises); True = fsync per record
    # (survives power loss, at the cost of gating every control-plane write
    # on disk latency; etcd batches fsyncs for the same reason).
    journal_fsync: bool = False
    # Control-plane replication (cluster/replication.py; --state-dir hosts):
    #   replication_wal_ring — journaled records retained in memory for
    #       GET /wal tailing. A standby that falls further behind than this
    #       re-bootstraps from a full snapshot (the etcd snapshot+WAL
    #       shape); size it above the peak write rate times the longest
    #       expected standby outage.
    #   replication_lease_seconds — the host-primacy lease duration: how
    #       long the primary may go silent before a standby whose WAL tail
    #       is ALSO disconnected auto-promotes. Short = fast failover,
    #       long = more tolerance for GC/IO pauses (split-brain guard:
    #       both conditions must hold — see replication.py).
    #   replication_poll_timeout — the standby's /wal long-poll window;
    #       bounds steady-state replication lag on a quiet primary.
    #   replication_max_lag_seconds — INV008 threshold: a standby lagging
    #       longer than this (records it has not applied aging past the
    #       bound) is a standing violation — failover from it would lose
    #       that much acknowledged history.
    replication_wal_ring: int = 65536
    replication_lease_seconds: float = 5.0
    replication_poll_timeout: float = 2.0
    replication_max_lag_seconds: float = 30.0
    # Node lifecycle (controllers/nodelifecycle.py + SimKubelet heartbeats):
    #   node_heartbeat_interval — kubelet Lease renewal period per node.
    #   node_grace_period — heartbeat silence before a node flips NotReady
    #       and takes the unreachable NoExecute taint (k8s default 40s).
    #   node_toleration_seconds — how long tainted pods get before eviction
    #       (k8s defaults 300s; shorter here because a broken ICI mesh
    #       stalls the whole gang for exactly this window before recovery
    #       can even begin).
    node_heartbeat_interval: float = 10.0
    node_grace_period: float = 40.0
    node_toleration_seconds: float = 30.0
    # Fleet introspection plane (observe/fleet.py + observe/invariants.py):
    # cadence of the standing invariant auditor AND the training_fleet_*
    # gauge republish, on the cluster clock. 0 disables both (the /fleet
    # route still serves the snapshot, just without live violations).
    fleet_audit_interval: float = 30.0
    # Multi-tenancy (tenancy/): the fair-share arbiter in front of the
    # gang solver. With no ClusterQueue/PriorityClass objects stored the
    # arbiter is a FIFO passthrough, so it is safe to leave enabled.
    #   default_priority_class — PriorityClass stamped onto PodGroups whose
    #       job names none (RunPolicy.scheduling_policy.priority_class);
    #       "" = unclassed (value 0, may not preempt).
    #   tenancy_starvation_seconds — a gang pending longer than this
    #       bypasses the priority tiers (FIFO front; never the quota gate)
    #       so low-priority work eventually runs. <=0 disables.
    #   tenancy_max_preemptions — a gang displaced this many times becomes
    #       immune to further preemption (the victim-side starvation
    #       guard; its checkpointed progress caps the work ever lost).
    tenancy_enabled: bool = True
    default_priority_class: str = ""
    tenancy_starvation_seconds: float = 600.0
    tenancy_max_preemptions: int = 3
    # Time-compressed fleet soak (soak/harness.py; `make bench-soak` and
    # the soak test tiers). The harness runs simulated days of fleet life
    # on the virtual clock with all five chaos tiers live:
    #   soak_hours — simulated fleet hours the soak covers (168 = a week).
    #   soak_arrival_per_minute — mean job arrival rate of the Poisson
    #       arrival process (heavy-tailed Pareto durations ride on top).
    #   soak_compression — duration compression: job durations and the
    #       soak's own control cadences (heartbeats, audits, resyncs) are
    #       divided by this, so the same fleet life fits fewer simulated
    #       seconds. 1.0 = uncompressed.
    #   soak_chaos — per-tier chaos intensity spec "pod=1,api=1,wire=1,
    #       node=1,host=1": 0 disables a tier, >1 scales its injection
    #       rate up. The host tier is BINARY (any value > 0 schedules the
    #       single mid-soak failover — the harness runs one warm standby,
    #       so there is exactly one failover to have). Parsed by
    #       parse_chaos_intensity().
    #   soak_seed — THE seed: every tier's schedule, the arrival trace,
    #       and all victim picks derive from it; two runs with the same
    #       seed produce identical kill/arrival logs (replay-pinned).
    soak_hours: float = 168.0
    soak_arrival_per_minute: float = 2.0
    soak_compression: float = 1.0
    soak_chaos: str = "pod=1,api=1,wire=1,node=1,host=1"
    soak_seed: int = 14
    # Probe/metrics HTTP port; 0 disables (reference --health-probe-bind-
    # address / --metrics-bind-address, collapsed to one server here).
    health_port: int = 0
    # Probe/metrics listener bind address. 127.0.0.1 keeps the in-process
    # sim private; real deployments set 0.0.0.0 so kubelet-style external
    # probes can reach /healthz (reference --health-probe-bind-address).
    health_bind_address: str = "127.0.0.1"
    # Bearer token required for /metrics when set (the secure-serving
    # analogue of the reference's cert-gated metrics endpoint,
    # pkg/cert/cert.go:45 + v2 main.go TLS flags — an in-process stack has
    # no certs to rotate, but the metrics surface still wants an auth gate).
    metrics_token: Optional[str] = None
    # Default images (reference pkg/config/config.go Config struct).
    pytorch_init_container_image: str = "alpine:3.10"
    init_container_max_tries: int = 100
    # Enable the v2 TrainJob/TrainingRuntime stack alongside v1.
    enable_v2: bool = True
    # Lease-based leader election (reference --enable-leader-election): a
    # standby operator stays quiet until the active one's lease expires or
    # is released. Identity defaults to a per-manager unique string.
    leader_elect: bool = False
    leader_identity: Optional[str] = None
    # Lease duration: how long a dead leader's lease blocks takeover
    # (controller-runtime LeaseDuration; renew interval is duration/3).
    leader_lease_duration: float = 15.0
    # Operator scale-out (controllers/leader.py ShardElector + the
    # follower-read wire client):
    #   operator_shards — partition reconcile ownership by namespace hash
    #       across this many `operator-shard-{i}` leases; every replica of
    #       the operator runs ACTIVE for its owned shards instead of one
    #       leader reconciling everything. 1 (default) keeps the single
    #       global leader election. Run with >= as many replicas as you
    #       want death-tolerance; shards > replicas is fine (rendezvous
    #       hashing spreads them).
    #   shard_takeover_grace — shard/membership lease duration: how long a
    #       dead replica's shards stay unowned before survivors take them
    #       over (short = fast handoff, long = tolerance for GC pauses).
    #       Also the INV010 bound: a shard unowned longer than this is a
    #       standing violation.
    #   read_from_standby — route the wire client's LISTs, watch sessions,
    #       /fleet, events, logs, and timelines to a standby address of
    #       the HA endpoint list (bounded staleness, X-Training-Staleness
    #       header); writes and single-object reads (lease arbitration,
    #       the optimistic-concurrency conflict arm) stay on the primary.
    operator_shards: int = 1
    shard_takeover_grace: float = 10.0
    read_from_standby: bool = False
    # Sharded write plane (cluster/shards.py StoreShardSet + the wire
    # shard router):
    #   store_shards — partition the HostStore by namespace hash (the same
    #       crc32 % N map the ShardElector uses, so a reconcile loop talks
    #       to exactly one write shard) into this many full stores, each
    #       with its own journal, WAL ring, warm standby, and epoch chain.
    #       1 (default) pins the exact single-store topology of every
    #       release before this knob existed.
    #   store_meta_shard — the shard index that owns cluster-scoped kinds
    #       (Node, PriorityClass, ClusterQueue, Lease) and empty-namespace
    #       objects; must name a valid shard (< store_shards).
    store_shards: int = 1
    store_meta_shard: int = 0

    def validate(self) -> None:
        unknown = [s for s in self.enabled_schemes if s not in ALL_SCHEMES]
        if unknown:
            raise ValueError(f"unknown scheme(s) {unknown}; choose from {ALL_SCHEMES}")
        if self.gang_scheduler_name not in GANG_SCHEDULERS:
            raise ValueError(
                f"unknown gang scheduler {self.gang_scheduler_name!r}; "
                f"choose from {GANG_SCHEDULERS}"
            )
        if self.controller_threads < 1:
            raise ValueError("controller_threads must be >= 1")
        if self.solver_kernel not in SOLVER_KERNELS:
            raise ValueError(
                f"unknown solver kernel {self.solver_kernel!r}; "
                f"choose from {SOLVER_KERNELS}"
            )
        if self.snapshot_selfcheck_every < 0:
            raise ValueError(
                "snapshot_selfcheck_every must be >= 0 (0 disables)"
            )
        if self.watch_ring_size < 1:
            # A zero-size ring would answer EVERY resume too-old: clients
            # still converge (relist arm) but every reconnect goes back to
            # O(cluster) — that degradation should be impossible to
            # configure by accident; disable resume client-side instead.
            raise ValueError("watch_ring_size must be >= 1")
        if self.wire_pipeline_depth < 0:
            raise ValueError("wire_pipeline_depth must be >= 0 (0 pins wire v1)")
        if self.coalesce_window_ms < 0:
            raise ValueError("coalesce_window_ms must be >= 0 (0 disables)")
        if self.list_page_limit < 0:
            raise ValueError("list_page_limit must be >= 0 (0 disables)")
        if self.compact_every < 1:
            raise ValueError("compact_every must be >= 1")
        if self.compact_max_journal_bytes < 0:
            raise ValueError("compact_max_journal_bytes must be >= 0 (0 disables)")
        if not 0.0 <= self.max_drain_fraction <= 1.0:
            raise ValueError("max_drain_fraction must be in [0, 1]")
        if self.aging_seconds < 0:
            raise ValueError("aging_seconds must be >= 0")
        if self.replication_wal_ring < 1:
            # A zero ring would force a full snapshot re-bootstrap on every
            # poll — replication that is all outage, no tail.
            raise ValueError("replication_wal_ring must be >= 1")
        if self.replication_lease_seconds <= 0:
            # A non-positive lease is permanently expired: any blip in the
            # WAL tail would promote the standby into a split brain.
            raise ValueError("replication_lease_seconds must be > 0")
        if self.replication_poll_timeout <= 0:
            raise ValueError("replication_poll_timeout must be > 0")
        if self.replication_max_lag_seconds < 0:
            raise ValueError("replication_max_lag_seconds must be >= 0")
        if self.node_heartbeat_interval <= 0:
            raise ValueError("node_heartbeat_interval must be > 0")
        if self.node_grace_period <= self.node_heartbeat_interval:
            # A grace shorter than one heartbeat period marks every healthy
            # node NotReady between beats: permanent flapping, not detection.
            raise ValueError(
                "node_grace_period must exceed node_heartbeat_interval"
            )
        if self.node_toleration_seconds < 0:
            raise ValueError("node_toleration_seconds must be >= 0")
        if self.fleet_audit_interval < 0:
            raise ValueError("fleet_audit_interval must be >= 0 (0 disables)")
        if self.soak_hours <= 0:
            raise ValueError("soak_hours must be > 0")
        if self.soak_arrival_per_minute <= 0:
            raise ValueError("soak_arrival_per_minute must be > 0")
        if self.soak_compression <= 0:
            # Compression divides durations/cadences; zero or negative would
            # stretch every job to infinity (or reverse time).
            raise ValueError("soak_compression must be > 0")
        parse_chaos_intensity(self.soak_chaos)  # raises on a malformed spec
        if self.tenancy_max_preemptions < 0:
            raise ValueError("tenancy_max_preemptions must be >= 0")
        if self.operator_shards < 1:
            raise ValueError("operator_shards must be >= 1 (1 = unsharded)")
        if self.store_shards < 1:
            raise ValueError("store_shards must be >= 1 (1 = unsharded)")
        if not 0 <= self.store_meta_shard < self.store_shards:
            # Cluster-scoped kinds must land on a real shard: an
            # out-of-range meta-shard would route Nodes/Leases nowhere.
            raise ValueError(
                "store_meta_shard must be in [0, store_shards)"
            )
        if self.shard_takeover_grace <= 0:
            # A non-positive grace is a permanently expired shard lease:
            # every replica would fight over every shard every tick —
            # continuous handoff churn, not ownership.
            raise ValueError("shard_takeover_grace must be > 0")
        if self.leader_lease_duration <= 0:
            # A non-positive lease is permanently expired: leadership would
            # flap between candidates every tick, each transition firing a
            # full resync — duplicated reconciling, not HA.
            raise ValueError("leader_lease_duration must be > 0")
        if self.metrics_token is not None and not self.metrics_token.isascii():
            # HTTP header bytes are latin-1-decoded by the stdlib server;
            # a non-ASCII token can never round-trip through the comparison
            # consistently across clients — reject at config time instead of
            # hard-locking /metrics.
            raise ValueError("metrics_token must be ASCII")

    @classmethod
    def from_file(cls, path: str) -> "OperatorConfig":
        with open(path) as f:
            data = json.load(f)
        known = {f.name for f in fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown config key(s): {sorted(unknown)}")
        cfg = cls(**data)
        cfg.validate()
        return cfg


_current = OperatorConfig()


def current() -> OperatorConfig:
    """The process-wide config (reference package-global config.Config)."""
    return _current


def set_current(cfg: OperatorConfig) -> OperatorConfig:
    global _current
    cfg.validate()
    _current = cfg
    return cfg
