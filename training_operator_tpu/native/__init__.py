"""ctypes bindings for the native data-path core (dataio.cpp).

The shared library is compiled on demand with the host toolchain (g++) and
cached by source hash; environments without a compiler degrade cleanly —
`available()` returns False and the DataLoader keeps its numpy path. No
pybind11 dependency: the ABI is plain C, the marshalling is ctypes +
numpy's ctypes bridge.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile
import threading
from pathlib import Path
from typing import Optional

import numpy as np

from training_operator_tpu.utils.locks import TrackedLock

_SOURCE = Path(__file__).with_name("dataio.cpp")
_lock = TrackedLock("native.build")
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None

_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_I64P = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _cache_dir() -> Path:
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return Path(root) / "training_operator_tpu"


def _build() -> Optional[ctypes.CDLL]:
    src = _SOURCE.read_bytes()
    flags = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread"]
    # Cache key covers source AND compile command: changing flags (or the
    # file paths baked into the command) must not load a stale .so.
    tag = hashlib.sha256(src + "\0".join(flags).encode()).hexdigest()[:16]
    out = _cache_dir() / f"dataio-{tag}.so"
    if not out.exists():
        out.parent.mkdir(parents=True, exist_ok=True)
        tmp = out.with_suffix(f".tmp{os.getpid()}")
        cmd = [*flags, str(_SOURCE), "-o", str(tmp)]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(f"g++ failed: {proc.stderr[:500]}")
        os.replace(tmp, out)  # atomic: concurrent builders race benignly
    lib = ctypes.CDLL(str(out))
    lib.tod_gather_rows.restype = ctypes.c_int
    lib.tod_gather_rows.argtypes = [
        _I32P, ctypes.c_int64, ctypes.c_int64,
        _I64P, ctypes.c_int64, _I32P, ctypes.c_int32,
    ]
    lib.tod_pack_tokens.restype = ctypes.c_int
    lib.tod_pack_tokens.argtypes = [_I32P, ctypes.c_int64, ctypes.c_int64, _I32P]
    lib.tod_prefetcher_create.restype = ctypes.c_void_p
    lib.tod_prefetcher_create.argtypes = [
        _I32P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.tod_prefetcher_submit.restype = ctypes.c_int
    lib.tod_prefetcher_submit.argtypes = [
        ctypes.c_void_p, _I64P, ctypes.c_int64, _I32P,
    ]
    lib.tod_prefetcher_wait.restype = ctypes.c_int
    lib.tod_prefetcher_wait.argtypes = [ctypes.c_void_p]
    lib.tod_prefetcher_destroy.restype = None
    lib.tod_prefetcher_destroy.argtypes = [ctypes.c_void_p]
    return lib


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    if _lib is not None or _build_error is not None:
        return _lib
    with _lock:
        if _lib is None and _build_error is None:
            try:
                _lib = _build()
            except Exception as e:  # no compiler / sandboxed fs / bad cache
                _build_error = f"{type(e).__name__}: {e}"
    return _lib


def available() -> bool:
    """True when the native library built (or loaded from cache)."""
    return _get() is not None


def build_error() -> Optional[str]:
    """Why the native path is unavailable (None when it is)."""
    _get()
    return _build_error


def default_threads() -> int:
    return min(8, os.cpu_count() or 1)


def gather_rows(
    rows: np.ndarray,
    idx: np.ndarray,
    out: Optional[np.ndarray] = None,
    threads: Optional[int] = None,
) -> np.ndarray:
    """out[i] = rows[idx[i]] via the threaded native gather. `rows` must be a
    C-contiguous int32 [N, R] array (an np.memmap over a token file counts);
    raises if the native library is unavailable — callers gate on
    `available()`."""
    lib = _get()
    if lib is None:
        raise RuntimeError(f"native dataio unavailable: {_build_error}")
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    if out is None:
        out = np.empty((len(idx), rows.shape[1]), dtype=np.int32)
    elif out.shape != (len(idx), rows.shape[1]) or out.dtype != np.int32:
        # The C ABI takes no output capacity — a short buffer would be
        # silent out-of-bounds heap writes, so shape is checked here.
        raise ValueError(
            f"out must be int32 {(len(idx), rows.shape[1])}, "
            f"got {out.dtype} {out.shape}"
        )
    rc = lib.tod_gather_rows(
        rows, rows.shape[0], rows.shape[1], idx, len(idx), out,
        threads or default_threads(),
    )
    if rc != 0:
        raise ValueError(f"tod_gather_rows rc={rc} (index out of range?)")
    return out


class Prefetcher:
    """Background gather pipeline over a fixed row arena: `submit` the next
    batch's shuffle indices while the device runs the current step; `wait`
    returns the filled staging buffer. The arena reference is held so the
    memory outlives the worker thread."""

    def __init__(self, rows: np.ndarray, threads: Optional[int] = None):
        lib = _get()
        if lib is None:
            raise RuntimeError(f"native dataio unavailable: {_build_error}")
        self._lib = lib
        self._rows = np.ascontiguousarray(rows, dtype=np.int32)
        self._handle = lib.tod_prefetcher_create(
            self._rows, self._rows.shape[0], self._rows.shape[1],
            threads or default_threads(),
        )
        if not self._handle:
            raise RuntimeError("tod_prefetcher_create failed")
        self._out: Optional[np.ndarray] = None

    def submit(self, idx: np.ndarray) -> None:
        if self._out is not None:
            raise RuntimeError("submit while a gather is in flight")
        idx = np.ascontiguousarray(idx, dtype=np.int64)
        out = np.empty((len(idx), self._rows.shape[1]), dtype=np.int32)
        rc = self._lib.tod_prefetcher_submit(self._handle, idx, len(idx), out)
        if rc != 0:
            raise RuntimeError(f"tod_prefetcher_submit rc={rc}")
        self._out = out

    def wait(self) -> np.ndarray:
        if self._out is None:
            raise RuntimeError("wait without a submitted gather")
        rc = self._lib.tod_prefetcher_wait(self._handle)
        out, self._out = self._out, None
        if rc != 0:
            raise RuntimeError(f"tod_prefetcher_wait rc={rc}")
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.tod_prefetcher_destroy(self._handle)
            self._handle = None

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
