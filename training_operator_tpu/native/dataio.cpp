// Native host-side data path for the trainer's DataLoader.
//
// The reference's data plane rides on torch DataLoader worker *processes*
// (hf_llm_training.py -> transformers.Trainer); a TPU host feeding one or
// more chips wants the opposite design: no pickling/IPC, just a
// memory-bandwidth-bound gather of shuffled rows out of a (possibly
// memory-mapped) token arena into a contiguous staging buffer that
// jax.device_put can DMA from, running on real OS threads outside the
// Python GIL so it overlaps the device step.
//
// C ABI (consumed via ctypes from training_operator_tpu/native/__init__.py):
//   tod_gather_rows     threaded strided row gather (int32 rows)
//   tod_pack_tokens     flat token stream -> [n, row] matrix
//   tod_prefetcher_*    double-buffered background gather pipeline
//
// Built on demand by native/__init__.py _build():
//   g++ -O3 -std=c++17 -shared -fPIC -pthread
// (cached under ~/.cache/training_operator_tpu, keyed by source + command).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// Copy rows[idx[i]] for i in [0, n_idx) into out (contiguous [n_idx, row_len]).
// Returns 0 on success, -1 on bad arguments. Bounds-checks every index so a
// corrupt shuffle order cannot scribble outside the arena.
int tod_gather_rows(const int32_t* base, int64_t n_rows, int64_t row_len,
                    const int64_t* idx, int64_t n_idx, int32_t* out,
                    int32_t n_threads) {
  if (base == nullptr || idx == nullptr || out == nullptr) return -1;
  if (n_rows < 0 || row_len <= 0 || n_idx < 0) return -1;
  for (int64_t i = 0; i < n_idx; ++i) {
    if (idx[i] < 0 || idx[i] >= n_rows) return -1;
  }
  const size_t row_bytes = static_cast<size_t>(row_len) * sizeof(int32_t);
  if (n_threads <= 1 || n_idx < 2 * n_threads) {
    for (int64_t i = 0; i < n_idx; ++i) {
      std::memcpy(out + i * row_len, base + idx[i] * row_len, row_bytes);
    }
    return 0;
  }
  std::vector<std::thread> ts;
  ts.reserve(n_threads);
  const int64_t per = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = std::min(n_idx, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(out + i * row_len, base + idx[i] * row_len, row_bytes);
      }
    });
  }
  for (auto& t : ts) t.join();
  return 0;
}

// Pack the first n_rows*(row_len) tokens of a flat stream into [n_rows,
// row_len] (the Python side computes n_rows = len(stream) // row_len and
// drops the remainder). One big memcpy — here for ABI completeness so a
// caller can stage straight from an mmap'd token file.
int tod_pack_tokens(const int32_t* stream, int64_t n_rows, int64_t row_len,
                    int32_t* out) {
  if (stream == nullptr || out == nullptr || n_rows < 0 || row_len <= 0)
    return -1;
  std::memcpy(out, stream,
              static_cast<size_t>(n_rows) * row_len * sizeof(int32_t));
  return 0;
}

// ---------------------------------------------------------------------------
// Background prefetcher: one worker thread, one request slot, one result
// slot. The Python loader submits the NEXT batch's indices while the device
// runs the CURRENT step; wait() blocks only if the gather hasn't finished.
// Double buffering comes from the caller alternating two staging buffers.

struct TodPrefetcher {
  const int32_t* base;
  int64_t n_rows;
  int64_t row_len;
  int32_t n_threads;

  std::mutex mu;
  std::condition_variable cv;
  std::thread worker;

  // Request slot (guarded by mu).
  std::vector<int64_t> req_idx;
  int32_t* req_out = nullptr;
  bool has_req = false;
  // True from submit until the result is consumed by wait — this is what
  // distinguishes "worker is mid-gather" (has_req already false, result
  // not yet posted) from "nothing submitted". Without it, a wait() landing
  // in that window reads as a protocol error and the caller may free the
  // staging buffer while the worker is still writing into it.
  bool in_flight = false;
  // Result slot (guarded by mu).
  bool has_result = false;
  int result_rc = 0;
  bool stop = false;

  void run() {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      cv.wait(lk, [&] { return has_req || stop; });
      if (stop) return;
      std::vector<int64_t> idx = std::move(req_idx);
      int32_t* out = req_out;
      has_req = false;
      lk.unlock();
      int rc = tod_gather_rows(base, n_rows, row_len, idx.data(),
                               static_cast<int64_t>(idx.size()), out,
                               n_threads);
      lk.lock();
      result_rc = rc;
      has_result = true;
      cv.notify_all();
    }
  }
};

void* tod_prefetcher_create(const int32_t* base, int64_t n_rows,
                            int64_t row_len, int32_t n_threads) {
  if (base == nullptr || n_rows < 0 || row_len <= 0) return nullptr;
  auto* p = new TodPrefetcher();
  p->base = base;
  p->n_rows = n_rows;
  p->row_len = row_len;
  p->n_threads = n_threads;
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Submit a gather of idx[0..n_idx) into out. Returns -2 if a request is
// already in flight (the caller must wait() first), -1 on bad args.
int tod_prefetcher_submit(void* handle, const int64_t* idx, int64_t n_idx,
                          int32_t* out) {
  auto* p = static_cast<TodPrefetcher*>(handle);
  if (p == nullptr || idx == nullptr || out == nullptr || n_idx < 0) return -1;
  std::lock_guard<std::mutex> lk(p->mu);
  if (p->in_flight) return -2;
  p->req_idx.assign(idx, idx + n_idx);
  p->req_out = out;
  p->has_req = true;
  p->in_flight = true;
  p->cv.notify_all();
  return 0;
}

// Block until the in-flight gather completes; returns its rc, or -2 if
// nothing was submitted.
int tod_prefetcher_wait(void* handle) {
  auto* p = static_cast<TodPrefetcher*>(handle);
  if (p == nullptr) return -1;
  std::unique_lock<std::mutex> lk(p->mu);
  if (!p->in_flight) return -2;
  p->cv.wait(lk, [&] { return p->has_result; });
  p->has_result = false;
  p->in_flight = false;
  return p->result_rc;
}

void tod_prefetcher_destroy(void* handle) {
  auto* p = static_cast<TodPrefetcher*>(handle);
  if (p == nullptr) return;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
    p->cv.notify_all();
  }
  p->worker.join();
  delete p;
}

}  // extern "C"
