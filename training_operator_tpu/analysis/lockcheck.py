"""Static lock/ownership analyzer — the compile-time half of the
concurrency-discipline plane (utils/locks.py is the runtime half).

The control plane is multi-threaded across ~a dozen modules (store,
apiserver, the four wire modules, replication tailer, metrics, timelines,
chaos), and "which fields may be touched off the owning thread" must be
checkable, not tribal. One AST pass over the tree infers, per class (and
per module for module-level locks):

  - the set of locks the scope owns (attributes assigned from
    `TrackedLock`/`TrackedRLock`/`TrackedCondition` — or the raw
    `threading` primitives CL008 is busy rejecting), with Condition
    attributes resolved to the lock they share;
  - the lock -> guarded-field map: fields written at least once inside a
    `with self._lock:` block, attributed to the innermost owned lock;
  - the static lock-order graph: lock B acquired lexically inside a
    `with A:` body, resolved across `self.helper()` calls one level deep;
  - thread entry points (Thread targets, ticker/callback registrations,
    `do_*` HTTP handler methods) — the signal that a class's fields are
    actually reachable from more than one thread.

Rules (ERROR; `make lint` runs this after codelint):

  CL008 raw-lock-outside-locks-module    `threading.Lock()/RLock()/
        Condition()` constructed anywhere but utils/locks.py. Every lock
        goes through the tracked factories so the runtime witness can see
        it and the order-class catalog stays one greppable file.
  CL009 blocking-call-under-lock    wire I/O (`request`/`getresponse`/
        `urlopen`/socket verbs), `os.fsync`, `subprocess.*`, `time.sleep`,
        or a no-timeout `.wait()` reached while a lock is held (directly
        or via a helper called one level deep under the lock). A blocked
        lock holder stalls every thread behind it — the PR 15
        read/write-token coupling class.
  CL010 static-lock-order-cycle    the per-file acquisition graph contains
        a cycle (lock A taken under B somewhere, B under A elsewhere):
        a potential deadlock the runtime witness would only catch when
        the interleaving actually happens.
  CL011 guarded-field-write-outside-lock    a field written under a lock
        everywhere else is written WITHOUT it in a class with thread
        entry points (the PR 2 `RemoteRuntime._timers` heap-race class).
        `__init__`-time writes are exempt — no second thread exists yet.

Exemptions are in-file pragmas, one reviewed line of code each:

    some_call()  # lockcheck: allow CL009 — journal order IS write order

The pragma may sit on the flagged line or alone on the line above; the
reason (after an em/en dash or ':') is MANDATORY — a bare pragma is itself
a finding. `python -m training_operator_tpu.analysis.lockcheck --report`
emits the inferred lock->field map and the order graph as JSON for review
(`make lockcheck-report`).
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from training_operator_tpu.analysis.codelint import Finding, _package_rel

# The one module allowed to construct raw threading primitives (CL008) —
# it IS the factory seam the rule funnels everyone through.
LOCKS_MODULE_SUFFIX = "utils/locks.py"

RAW_LOCK_CTORS = ("Lock", "RLock", "Condition")
TRACKED_CTORS = ("TrackedLock", "TrackedRLock", "TrackedCondition")

# Attribute-call verbs that block the calling thread (CL009). Socket and
# http.client I/O, durability fsync, and the subprocess family; `run` &co
# are matched only on a literal `subprocess` receiver (too generic
# otherwise).
BLOCKING_ATTR_CALLS = (
    "fsync", "request", "getresponse", "urlopen", "sendall", "recv",
    "create_connection",
)
SUBPROCESS_VERBS = ("run", "call", "check_call", "check_output", "Popen",
                    "communicate")

# Mutating container verbs: a call `self.field.append(...)` counts as a
# write to `field` for the guarded-field map.
MUTATING_METHODS = (
    "append", "appendleft", "extend", "add", "update", "setdefault", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "insert",
    "move_to_end",
)

# Callback-registration verbs whose `self.<m>` argument marks `m` (and the
# class) as reachable from another thread (codelint CL001/CL003 lineage:
# tickers and timers run on the cluster loop, watch callbacks on the
# session thread).
CALLBACK_REGISTRARS = (
    "add_ticker", "schedule_after", "subscribe", "attach", "register",
    "add_done_callback", "pre_disrupt",
)

_PRAGMA_RE = re.compile(
    r"#\s*lockcheck:\s*allow\s+(CL\d{3})(?:\s*(?:[—–:-]|--)\s*(.*\S))?\s*$"
)


# -- pragma allowlist ------------------------------------------------------


class _Allowlist:
    """Per-file `# lockcheck: allow CLxxx — reason` pragmas. A finding on
    line L is suppressed by a pragma on L or on a standalone comment line
    immediately above. Pragmas without a reason are findings themselves —
    every exemption is a reviewed, justified line."""

    def __init__(self, path: str, source: str):
        self.entries: Dict[int, Tuple[str, Optional[str]]] = {}
        self.bare: List[Tuple[int, str]] = []
        for i, line in enumerate(source.splitlines(), start=1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            rule, reason = m.group(1), m.group(2)
            if not reason:
                self.bare.append((i, rule))
                continue
            self.entries[i] = (rule, reason)
            # A standalone pragma comment covers the next line.
            if line.lstrip().startswith("#"):
                self.entries[i + 1] = (rule, reason)

    def allows(self, line: int, rule: str) -> bool:
        for probe in (line, line - 1):
            entry = self.entries.get(probe)
            if entry and entry[0] == rule:
                return True
        return False

    def findings(self, path: str) -> List[Finding]:
        return [
            Finding(path, line, "CL000",
                    f"allowlist pragma for {rule} carries no reason; write "
                    f"`# lockcheck: allow {rule} — <why this is safe>`")
            for line, rule in self.bare
        ]


# -- per-scope lock model --------------------------------------------------


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` -> 'X' (None otherwise)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_raw_lock_ctor(call: ast.Call) -> Optional[str]:
    f = call.func
    if (isinstance(f, ast.Attribute) and f.attr in RAW_LOCK_CTORS
            and isinstance(f.value, ast.Name) and f.value.id == "threading"):
        return f.attr
    return None


def _is_tracked_ctor(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name) and f.id in TRACKED_CTORS:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in TRACKED_CTORS:
        return f.attr
    return None


def _lock_ctor_kind(value: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
    """If `value` constructs a lock, return (kind, shared_lock_attr) where
    kind in {lock, rlock, cond} and shared_lock_attr is the `self.Y` a
    Condition was built over (None otherwise)."""
    if not isinstance(value, ast.Call):
        return None
    name = _is_raw_lock_ctor(value) or _is_tracked_ctor(value)
    if name is None:
        return None
    kind = {"Lock": "lock", "TrackedLock": "lock",
            "RLock": "rlock", "TrackedRLock": "rlock",
            "Condition": "cond", "TrackedCondition": "cond"}[name]
    shared = None
    if kind == "cond":
        args = list(value.args) + [k.value for k in value.keywords
                                   if k.arg == "lock"]
        if args:
            shared = _self_attr(args[0])
    return kind, shared


@dataclass
class _ScopeModel:
    """Lock model for one class (or the module top level)."""

    qualname: str                       # 'Class' or '<module>'
    lock_attrs: Dict[str, str] = field(default_factory=dict)   # attr -> kind
    cond_alias: Dict[str, str] = field(default_factory=dict)   # cond -> lock
    # field -> {lock names it was written under}
    writes_under: Dict[str, Set[str]] = field(default_factory=dict)
    # field -> [(line, method)] writes with NO owned lock held (non-init)
    writes_outside: Dict[str, List[Tuple[int, str]]] = field(default_factory=dict)
    # (held_lock, acquired_lock) -> line of first observation
    order_edges: Dict[Tuple[str, str], int] = field(default_factory=dict)
    # (line, description, lock) blocking-call candidates
    blocking: List[Tuple[int, str, str]] = field(default_factory=list)
    entry_points: Set[str] = field(default_factory=set)

    def resolve(self, attr: str) -> str:
        return self.cond_alias.get(attr, attr)

    def guarded_fields(self) -> Dict[str, str]:
        """field -> lock, for fields written under exactly one lock."""
        return {
            f: next(iter(ls))
            for f, ls in sorted(self.writes_under.items())
            if len(ls) == 1
        }


class _FileAnalysis:
    """One file's lock model + findings."""

    def __init__(self, path: str, rel: str, tree: ast.Module, source: str):
        self.path = path
        self.rel = rel
        self.tree = tree
        self.allow = _Allowlist(path, source)
        self.scopes: List[_ScopeModel] = []
        self.raw_ctors: List[Tuple[int, str]] = []
        self._collect()

    # -- collection -------------------------------------------------------

    def _collect(self) -> None:
        module_scope = _ScopeModel("<module>")
        module_body: List[ast.stmt] = []
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.scopes.append(self._collect_class(node))
            else:
                module_body.append(node)
        # Module-level locks (wire.py's codec/event-bytes locks): names
        # assigned from a lock ctor, acquired via bare `with _name:`.
        for node in module_body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                got = _lock_ctor_kind(node.value)
                if got:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            module_scope.lock_attrs[t.id] = got[0]
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                ctor = _is_raw_lock_ctor(node)
                if ctor:
                    self.raw_ctors.append((node.lineno, ctor))
        if module_scope.lock_attrs:
            self._walk_functions(
                module_scope, self.tree.body, module_is_scope=True
            )
        self.scopes.append(module_scope)

    def _collect_class(self, cls: ast.ClassDef) -> _ScopeModel:
        model = _ScopeModel(cls.name)
        methods = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # Nested classes (the wire_server request-handler factories) fold
        # into the parent model: same threading story, same file.
        for inner in [n for n in cls.body if isinstance(n, ast.ClassDef)]:
            for n in inner.body:
                if (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and n.name not in methods):
                    methods[n.name] = n
        # Pass 1: lock attributes + condition aliases + entry points.
        for m in methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    got = _lock_ctor_kind(node.value)
                    if got is None:
                        continue
                    kind, shared = got
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr:
                            model.lock_attrs[attr] = kind
                            if kind == "cond" and shared:
                                model.cond_alias[attr] = shared
                if isinstance(node, ast.Call):
                    self._note_entry_points(model, node)
        for name in methods:
            if name.startswith("do_") or name in ("handle", "handle_one_request"):
                model.entry_points.add(name)
        # Pass 2: walk each method with a held-lock stack.
        helper_calls: List[Tuple[List[str], str]] = []
        for name, m in methods.items():
            self._walk_stmts(
                model, m.body, held=[], method=name,
                helper_calls=helper_calls,
            )
        # One-level helper resolution: a helper's OWN top-level effects
        # (lock acquisitions, blocking calls) also happen under every lock
        # its caller held at the call site.
        helper_effects = {
            name: self._helper_effects(model, m)
            for name, m in methods.items()
        }
        for held, helper in helper_calls:
            effects = helper_effects.get(helper)
            if not effects:
                continue
            acquired, blocking = effects
            for a in held:
                for b, line in acquired:
                    if a != b:
                        model.order_edges.setdefault((a, b), line)
            for line, desc in blocking:
                model.blocking.append(
                    (line, f"{desc} (in {helper}(), reached under lock)",
                     held[-1])
                )
        return model

    def _note_entry_points(self, model: _ScopeModel, call: ast.Call) -> None:
        f = call.func
        # threading.Thread(target=self.m) / Thread(target=self.m)
        is_thread = (
            (isinstance(f, ast.Attribute) and f.attr == "Thread")
            or (isinstance(f, ast.Name) and f.id == "Thread")
        )
        if is_thread:
            for k in call.keywords:
                if k.arg == "target":
                    attr = _self_attr(k.value)
                    model.entry_points.add(attr or "<thread>")
            return
        verb = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if verb in CALLBACK_REGISTRARS:
            for arg in list(call.args) + [k.value for k in call.keywords]:
                attr = _self_attr(arg)
                if attr:
                    model.entry_points.add(attr)
                elif isinstance(arg, ast.Lambda):
                    model.entry_points.add("<lambda>")

    def _with_locks(self, model: _ScopeModel, node: ast.With,
                    module_scope: bool = False) -> List[str]:
        out = []
        for item in node.items:
            expr = item.context_expr
            attr = _self_attr(expr)
            if attr is None and module_scope and isinstance(expr, ast.Name):
                attr = expr.id
            if attr is not None and model.resolve(attr) in model.lock_attrs:
                out.append(model.resolve(attr))
        return out

    def _walk_stmts(self, model: _ScopeModel, body: Sequence[ast.stmt],
                    held: List[str], method: str,
                    helper_calls: List[Tuple[List[str], str]],
                    module_scope: bool = False) -> None:
        for node in body:
            if isinstance(node, ast.With):
                got = self._with_locks(model, node, module_scope)
                for b in got:
                    for a in held:
                        if a != b:
                            model.order_edges.setdefault((a, b), node.lineno)
                self._walk_stmts(model, node.body, held + got, method,
                                 helper_calls, module_scope)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested defs (handler closures) are their own call-time
                # scope: locks held NOW are not held when they run.
                self._walk_stmts(model, node.body, [], f"{method}.{node.name}",
                                 helper_calls, module_scope)
                continue
            if isinstance(node, ast.ClassDef):
                continue
            self._scan_expr(model, node, held, method, helper_calls)
            for fld in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(node, fld, None)
                if not sub:
                    continue
                if fld == "handlers":
                    for h in sub:
                        self._walk_stmts(model, h.body, held, method,
                                         helper_calls, module_scope)
                else:
                    self._walk_stmts(model, sub, held, method,
                                     helper_calls, module_scope)

    def _scan_expr(self, model: _ScopeModel, stmt: ast.stmt, held: List[str],
                   method: str,
                   helper_calls: List[Tuple[List[str], str]]) -> None:
        """Field writes, blocking calls, and helper calls in one statement
        (its own expressions only — nested stmt bodies recurse through
        _walk_stmts with their own held-lock context)."""
        in_init = method in ("__init__", "__post_init__", "__init_subclass__")
        own_locks_held = [h for h in held if h in model.lock_attrs]
        # Writes: assignment / augassign / subscript-store / mutating call.
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            attr = _self_attr(base)
            if attr is None or attr in model.lock_attrs:
                continue
            self._note_write(model, attr, stmt.lineno, method, in_init,
                             own_locks_held)
        for node in _expr_nodes(stmt):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # self.field.mutator(...) counts as a write to field.
            if isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS:
                attr = _self_attr(f.value)
                if attr and attr not in model.lock_attrs:
                    self._note_write(model, attr, node.lineno, method,
                                     in_init, own_locks_held)
            # self.helper(...) for one-level resolution.
            if held:
                attr = _self_attr(f) if isinstance(f, ast.Attribute) else None
                if attr:
                    helper_calls.append((list(held), attr))
                desc = _blocking_desc(node)
                if desc:
                    model.blocking.append((node.lineno, desc, held[-1]))

    def _note_write(self, model: _ScopeModel, attr: str, line: int,
                    method: str, in_init: bool,
                    own_locks_held: List[str]) -> None:
        if own_locks_held:
            model.writes_under.setdefault(attr, set()).add(own_locks_held[-1])
        elif not in_init:
            model.writes_outside.setdefault(attr, []).append((line, method))

    def _helper_effects(self, model: _ScopeModel, fn) -> Optional[
            Tuple[List[Tuple[str, int]], List[Tuple[int, str]]]]:
        """(locks acquired, blocking calls) at a method's top level — what
        a caller holding a lock inherits from calling it."""
        acquired: List[Tuple[str, int]] = []
        blocking: List[Tuple[int, str]] = []

        def walk(body: Sequence[ast.stmt]) -> None:
            for node in body:
                if isinstance(node, ast.With):
                    for b in self._with_locks(model, node):
                        acquired.append((b, node.lineno))
                    walk(node.body)
                    continue
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                for sub in _expr_nodes(node):
                    if isinstance(sub, ast.Call):
                        desc = _blocking_desc(sub)
                        if desc:
                            blocking.append((sub.lineno, desc))
                for fld in ("body", "orelse", "finalbody"):
                    if getattr(node, fld, None):
                        walk(getattr(node, fld))
                for h in getattr(node, "handlers", []) or []:
                    walk(h.body)

        walk(fn.body)
        if acquired or blocking:
            return acquired, blocking
        return None

    def _walk_functions(self, model: _ScopeModel, body: Sequence[ast.stmt],
                        module_is_scope: bool) -> None:
        """Module-scope pass: every top-level function walked against the
        module's lock names (class methods were handled per class)."""
        helper_calls: List[Tuple[List[str], str]] = []
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_stmts(model, node.body, [], node.name,
                                 helper_calls, module_scope=True)

    # -- findings ---------------------------------------------------------

    def findings(self) -> List[Finding]:
        out: List[Finding] = list(self.allow.findings(self.path))
        in_locks_module = self.rel.endswith(LOCKS_MODULE_SUFFIX)
        if not in_locks_module:
            for line, ctor in self.raw_ctors:
                out.append(Finding(
                    self.path, line, "CL008",
                    f"raw threading.{ctor}() outside utils/locks.py; use "
                    f"locks.Tracked{'Lock' if ctor == 'Lock' else ctor} so "
                    f"the runtime witness can see it",
                ))
        for model in self.scopes:
            prefix = "" if model.qualname == "<module>" else f"{model.qualname}."
            for (line, desc, lock) in model.blocking:
                out.append(Finding(
                    self.path, line, "CL009",
                    f"blocking {desc} while holding {prefix}{lock} stalls "
                    f"every thread queued on that lock",
                ))
            for cycle in _cycles(model.order_edges):
                line = min(
                    model.order_edges.get((a, b), 1 << 30)
                    for a, b in zip(cycle, cycle[1:] + cycle[:1])
                    if (a, b) in model.order_edges
                )
                out.append(Finding(
                    self.path, line, "CL010",
                    f"lock-order cycle {' -> '.join(prefix + c for c in cycle)}"
                    f" -> {prefix}{cycle[0]}: opposite acquisition orders "
                    f"deadlock under the right interleaving",
                ))
            if not model.entry_points:
                continue
            guarded = model.guarded_fields()
            for fld, sites in sorted(model.writes_outside.items()):
                lock = guarded.get(fld)
                if lock is None:
                    continue
                for line, method in sites:
                    out.append(Finding(
                        self.path, line, "CL011",
                        f"write to {prefix}{fld} outside {prefix}{lock} "
                        f"(guarded everywhere else; class has thread entry "
                        f"points {sorted(model.entry_points)})",
                    ))
        kept: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()
        for f in out:
            if f.rule_id != "CL000" and self.allow.allows(f.line, f.rule_id):
                continue
            # Dedup (line, rule): a blocking call both inside a helper and
            # directly under a lock reports once.
            key = (f.line, f.rule_id)
            if key in seen:
                continue
            seen.add(key)
            kept.append(f)
        kept.sort(key=lambda f: (f.line, f.rule_id))
        return kept

    # -- report -----------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        classes: Dict[str, Any] = {}
        edges: List[Dict[str, Any]] = []
        for model in self.scopes:
            if not (model.lock_attrs or model.entry_points):
                continue
            lock_to_fields: Dict[str, List[str]] = {}
            for fld, lock in model.guarded_fields().items():
                lock_to_fields.setdefault(lock, []).append(fld)
            classes[model.qualname] = {
                "locks": {a: k for a, k in sorted(model.lock_attrs.items())},
                "condition_aliases": dict(sorted(model.cond_alias.items())),
                "guarded_fields": {
                    k: sorted(v) for k, v in sorted(lock_to_fields.items())
                },
                "entry_points": sorted(model.entry_points),
            }
            for (a, b), line in sorted(model.order_edges.items()):
                edges.append({
                    "scope": model.qualname, "held": a, "acquired": b,
                    "line": line,
                })
        return {"classes": classes, "order_edges": edges}


def _cycles(edges: Dict[Tuple[str, str], int]) -> List[List[str]]:
    """Elementary cycles in the (small) per-file order graph, deduplicated
    by node set, smallest-first rotation for stable reporting."""
    adj: Dict[str, Set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
    seen: Set[frozenset] = set()
    out: List[List[str]] = []

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key not in seen:
                    seen.add(key)
                    rot = path.index(min(path))
                    out.append(path[rot:] + path[:rot])
            elif nxt not in path and nxt > start:
                # Only explore nodes ordered after `start`: each cycle is
                # found exactly once, from its smallest node.
                dfs(start, nxt, path + [nxt])

    for start in sorted(adj):
        dfs(start, start, [start])
    return out


def _expr_nodes(stmt: ast.stmt) -> Iterator[ast.AST]:
    """The statement's OWN expression subtree: header expressions only
    (nested statement bodies carry a different held-lock context), and no
    descent into lambdas / nested defs (they run later, locks released)."""
    stack = [c for c in ast.iter_child_nodes(stmt)
             if not isinstance(c, (ast.stmt, ast.excepthandler))]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _blocking_desc(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id == "urlopen":
            return "urlopen()"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    recv = f.value
    if isinstance(recv, ast.Name) and recv.id == "subprocess" \
            and f.attr in SUBPROCESS_VERBS:
        return f"subprocess.{f.attr}()"
    if f.attr == "sleep" and isinstance(recv, ast.Name) \
            and recv.id in ("time", "_time", "_t"):
        return "time.sleep()"
    if f.attr in BLOCKING_ATTR_CALLS:
        return f".{f.attr}()"
    if f.attr == "wait" and not call.args and not any(
            k.arg == "timeout" for k in call.keywords):
        return "no-timeout .wait()"
    return None


# -- entry points ----------------------------------------------------------


def analyze_source(path: str, source: str,
                   package_rel: Optional[str] = None) -> _FileAnalysis:
    rel = (package_rel if package_rel is not None else path).replace(os.sep, "/")
    tree = ast.parse(source, filename=path)
    return _FileAnalysis(path, rel, tree, source)


def check_source(path: str, source: str,
                 package_rel: Optional[str] = None) -> List[Finding]:
    try:
        fa = analyze_source(path, source, package_rel)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "CL000", f"syntax error: {e.msg}")]
    return fa.findings()


def _iter_files(paths: Sequence[str]) -> Iterator[Tuple[str, str]]:
    for root in paths:
        if os.path.isfile(root):
            files, base = [root], os.path.dirname(root)
        else:
            base = root
            files = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(
                    os.path.join(dirpath, f) for f in sorted(filenames)
                    if f.endswith(".py")
                )
        for f in sorted(files):
            yield f, base


def check_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for f, base in _iter_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        findings.extend(check_source(f, src, package_rel=_package_rel(f, base)))
    return findings


def report_paths(paths: Sequence[str]) -> Dict[str, Any]:
    """The `--report` JSON: per-file lock->field maps + the merged
    acquisition-order graph (`make lockcheck-report`)."""
    files: Dict[str, Any] = {}
    merged_edges: List[Dict[str, Any]] = []
    for f, base in _iter_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        rel = _package_rel(f, base)
        try:
            fa = analyze_source(f, src, package_rel=rel)
        except SyntaxError:
            continue
        rep = fa.report()
        if rep["classes"]:
            files[rel] = rep["classes"]
        for e in rep["order_edges"]:
            merged_edges.append({**e, "file": rel})
    return {"files": files, "order_edges": merged_edges}


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    want_report = "--report" in args
    if want_report:
        args.remove("--report")
    if not args:
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        args = [pkg_root]
    if want_report:
        print(json.dumps(report_paths(args), indent=1, sort_keys=True))
        return 0
    findings = check_paths(args)
    for f in findings:
        print(f.render())
    if findings:
        print(f"lockcheck: {len(findings)} finding(s)")
        return 1
    print("lockcheck: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
