"""Diagnostic model + the rule catalog.

Every rule has a stable id (`TPU001`), a short slug, a default severity, and
remediation text. The catalog is the single source of truth: the CLI's
`--rules` listing and the README reference table are generated from it, and
`Diagnostic` construction validates ids against it so a rule can't fire
without being documented.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class Severity(str, enum.Enum):
    ERROR = "ERROR"
    WARN = "WARN"
    INFO = "INFO"


@dataclass(frozen=True)
class Rule:
    rule_id: str
    slug: str
    severity: Severity
    catches: str
    fix: str


# The rule catalog. Ids are append-only: retired rules keep their id reserved
# so historical annotations/metrics stay interpretable.
RULES: Dict[str, Rule] = {
    r.rule_id: r
    for r in [
        Rule(
            "TPU001", "topology-chip-mismatch", Severity.ERROR,
            "num_nodes x proc_per_node cannot tile the requested slice "
            "topology's chip count (nodes don't divide the chip grid, or an "
            "explicit procPerNode disagrees with chips-per-host)",
            "make numNodes x numProcPerNode equal topology chips x numSlices, "
            "or drop numProcPerNode and let the runtime derive it",
        ),
        Rule(
            "TPU002", "ici-contiguity-infeasible", Severity.ERROR,
            "the requested topology can never form a contiguous axis-aligned "
            "ICI sub-mesh: hosts don't tile the grid's minor axis, or no "
            "slice geometry in the inventory admits a single candidate "
            "placement",
            "request a topology whose minor axis is a multiple of "
            "chips-per-host, or match an inventory slice geometry",
        ),
        Rule(
            "TPU003", "mesh-axes-mismatch", Severity.ERROR,
            "the product of mlPolicy.tpu.mesh_axes does not equal total chips "
            "(topology chips x numSlices) — the trainer cannot build its mesh",
            "adjust mesh_axes so their product equals total chips",
        ),
        Rule(
            "TPU004", "nodes-slices-mismatch", Severity.ERROR,
            "numNodes is not divisible by numSlices (or numSlices < 1): "
            "slices cannot have equal worker counts",
            "set numNodes to a whole multiple of numSlices",
        ),
        Rule(
            "TPU005", "accelerator-topology-mismatch", Severity.WARN,
            "the accelerator name's chip-count suffix (e.g. v5e-8) disagrees "
            "with the declared topology's chip count",
            "rename the accelerator or fix the topology; the topology wins "
            "at placement time",
        ),
        Rule(
            "CAP001", "insufficient-inventory", Severity.ERROR,
            "the inventory snapshot cannot ever satisfy the request: fewer "
            "matching slices than numSlices, or no TPU slices at all",
            "shrink numSlices / pick a smaller topology, or grow the pool",
        ),
        Rule(
            "CAP002", "queue-oversubscribed", Severity.WARN,
            "total chip demand of queued gangs plus this job exceeds total "
            "inventory chips — the gang will queue behind others",
            "expect queueing; consider a smaller ask or more slices",
        ),
        Rule(
            "GANG001", "gang-never-placeable", Severity.ERROR,
            "a queued PodGroup's topology request fits no slice geometry in "
            "the inventory — it will sit Unschedulable forever",
            "delete or resize the stuck gang; it can never admit",
        ),
        Rule(
            "GANG002", "gang-capacity-deadlock", Severity.WARN,
            "queued whole-slice gangs collectively demand more slices than "
            "exist while each is individually placeable — admission order "
            "determines who starves",
            "rely on aging/drain reservations, or reduce concurrent gangs",
        ),
        Rule(
            "ENV001", "env-bootstrap-conflict", Severity.WARN,
            "user trainer env collides with operator-injected bootstrap "
            "variables (jax.distributed / PET_* / MASTER_* contract); the "
            "user value wins and can break coordinator discovery",
            "remove the colliding keys or rename your variables",
        ),
        Rule(
            "POL001", "elastic-range-invalid", Severity.ERROR,
            "torch elastic policy is unsatisfiable: min > max, min < 1, or "
            "the resolved node count falls outside [min, max]",
            "fix elastic_min_nodes/elastic_max_nodes to bracket numNodes",
        ),
        Rule(
            "POL002", "restart-policy-invalid", Severity.ERROR,
            "failure policy is malformed (negative max_restarts)",
            "set max_restarts >= 0",
        ),
        Rule(
            "RT001", "runtime-not-found", Severity.ERROR,
            "runtimeRef names a TrainingRuntime that does not exist in the "
            "catalog / cluster",
            "create the runtime or reference a built-in preset",
        ),
        Rule(
            "RT002", "no-trainer-template", Severity.WARN,
            "the runtime has no trainer-node replicated job; the default "
            "trainer template will be synthesized",
            "declare a trainer-node template in the runtime",
        ),
        Rule(
            "JOB001", "invalid-name", Severity.ERROR,
            "job name is not a valid DNS-1035 label (pod/service DNS names "
            "would be invalid)",
            "use lowercase alphanumerics and '-', start with a letter, "
            "<= 63 chars",
        ),
        Rule(
            "NODE001", "num-nodes-override-clamped", Severity.WARN,
            "trainer.numNodes override is not a whole multiple of the "
            "runtime's workers-per-slice; the workload builder will clamp it "
            "down to a whole slice count",
            "override in whole-slice steps (multiples of numNodes/numSlices)",
        ),
        Rule(
            "NODE002", "restart-budget-below-host-failure", Severity.WARN,
            "a multi-host TPU job's restart budget cannot absorb even one "
            "host failure: torch maxRestarts is 0 (explicitly, or unset — "
            "torchrun's default is 0) or the trainer template's restart "
            "policy is Never. Losing one host breaks the slice's ICI mesh; "
            "surviving workers then exit, and with zero budget those exits "
            "fail the job permanently",
            "set mlPolicy.torch.maxRestarts >= 1 (sized to host-failure "
            "rate x job duration), or use an OnFailure/ExitCode restart "
            "policy on the trainer template",
        ),
        Rule(
            "TEN001", "priority-class-not-found", Severity.ERROR,
            "the job names a PriorityClass that does not exist — it would "
            "silently run unclassed (value 0, never preempting), which is "
            "exactly the typo the k8s priority admission plugin rejects",
            "create the PriorityClass first, or name an existing one "
            "(tenancy.tpu.dev/priority-class label / "
            "schedulingPolicy.priorityClass)",
        ),
        Rule(
            "TEN002", "queue-can-never-fit", Severity.WARN,
            "the job's ClusterQueue can never admit its gang: the queue "
            "does not exist (the gang waits for it), or the gang's chip "
            "demand exceeds the queue's quota + borrowing limit — it "
            "would sit QuotaExceeded forever",
            "raise the queue's quota/borrowing above the gang's demand, "
            "shrink the gang, or route it to a bigger queue",
        ),
    ]
}


@dataclass(frozen=True)
class Diagnostic:
    rule_id: str
    severity: Severity
    message: str
    path: str = ""  # spec path, e.g. "trainer.numNodes"

    def __post_init__(self):
        if self.rule_id not in RULES:
            raise ValueError(f"undocumented rule id {self.rule_id!r}")

    @property
    def slug(self) -> str:
        return RULES[self.rule_id].slug

    def render(self) -> str:
        loc = f" [{self.path}]" if self.path else ""
        return f"{self.severity.value} {self.rule_id} {self.slug}{loc}: {self.message}"


@dataclass
class LintReport:
    """Ordered diagnostics for one lint target."""

    target: str = ""
    diagnostics: List[Diagnostic] = field(default_factory=list)

    def add(
        self,
        rule_id: str,
        message: str,
        path: str = "",
        severity: Optional[Severity] = None,
    ) -> None:
        self.diagnostics.append(
            Diagnostic(
                rule_id=rule_id,
                severity=severity or RULES[rule_id].severity,
                message=message,
                path=path,
            )
        )

    def extend(self, other: "LintReport") -> None:
        self.diagnostics.extend(other.diagnostics)

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.ERROR]

    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.WARN]

    def ok(self) -> bool:
        return not self.errors()

    def rule_ids(self) -> List[str]:
        return [d.rule_id for d in self.diagnostics]

    def has(self, rule_id: str) -> bool:
        return rule_id in self.rule_ids()

    def render(self) -> str:
        head = f"{self.target}: " if self.target else ""
        if not self.diagnostics:
            return f"{head}clean"
        return "\n".join(f"{head}{d.render()}" for d in self.diagnostics)
