"""Spec dry-run analysis: decide placement feasibility statically.

Pure functions over (TrainJob, resolved TrainingRuntime, optional inventory
snapshot, optional queued PodGroups) — no API writes, no clocks, no side
effects. The shape resolution mirrors the v2 plugin chain exactly
(runtime/plugins.py: TrainJob overrides win, workers-per-slice is fixed by
the runtime's base shape, non-divisible overrides clamp down to whole
slices), so what the analyzer accepts is what the reconciler would build.

ICI-contiguity feasibility reuses the packer's own candidate generation
(scheduler/candidates.py): a topology is placeable on a slice geometry iff
`enumerate_candidates` yields at least one host mask for it.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

from training_operator_tpu.analysis.diagnostics import LintReport
from training_operator_tpu.api.validation import is_dns1035_label
from training_operator_tpu.cluster.inventory import (
    TPU_RESOURCE,
    accel_family,
    topology_chips,
    try_parse_topology,
)
from training_operator_tpu.cluster.objects import PodGroupPhase
from training_operator_tpu.runtime.api import TRAINER_NODE, TrainingRuntime, TrainJob
from training_operator_tpu.scheduler.candidates import CandidateCache, host_grid_dims
from training_operator_tpu.tenancy.api import PRIORITY_CLASS_LABEL, QUEUE_LABEL

# Shared across lint invocations: geometry classes are few, and webhook-path
# lint runs per TrainJob create — re-enumerating per admission would be the
# only non-O(1) cost on that path. Enumerations are immutable, so sharing
# with concurrent readers is safe.
_candidates = CandidateCache()

# Operator-injected bootstrap env per policy family (controllers/jax.py,
# controllers/pytorch.py, runtime/plugins.py). A user key colliding with one
# of these silently wins (controllers use env.setdefault) and can break
# coordinator discovery — exactly the footgun ENV001 exists for.
JAX_INJECTED_ENV = frozenset({
    "PYTHONUNBUFFERED", "COORDINATOR_ADDRESS", "COORDINATOR_PORT",
    "NUM_PROCESSES", "PROCESS_ID", "TPU_ACCELERATOR", "TPU_NUM_SLICES",
    "TPU_SLICE_TOPOLOGY", "TPU_MESH_AXES", "TPU_SLICE_ID",
    "TPU_WORKER_ID_IN_SLICE", "TPU_WORKERS_PER_SLICE",
    "TPU_SLICE_COORDINATOR_ADDRESS", "TPU_SLICE_COORDINATOR_PORT",
    "MEGASCALE_COORDINATOR_ADDRESS", "MEGASCALE_PORT",
    "MEGASCALE_NUM_SLICES", "MEGASCALE_SLICE_ID",
})
TORCH_INJECTED_ENV = frozenset({
    "PYTHONUNBUFFERED", "MASTER_ADDR", "MASTER_PORT", "WORLD_SIZE", "RANK",
    "PET_NODE_RANK", "PET_NNODES", "PET_NPROC_PER_NODE", "PET_RDZV_ENDPOINT",
    "PET_RDZV_BACKEND", "PET_RDZV_ID", "PET_RDZV_CONF", "PET_STANDALONE",
    "PET_MAX_RESTARTS",
})

# (tpu_type, slice_topology, chips_per_host) -> number of such slices
SliceClasses = Dict[Tuple[str, str, int], int]


def slice_classes_from_nodes(nodes: Iterable) -> SliceClasses:
    """Geometry classes of the TPU slices in a node inventory (equal
    geometries share one candidate enumeration, snapshot.SliceInfo-style).
    Slices with unparseable topology labels are dropped — the analyzer runs
    against live label data and must not crash admission on a junk node."""
    slices: Dict[str, Tuple[str, str, int]] = {}
    for node in nodes:
        acc = getattr(node, "accelerator", None)
        if acc is None or acc.kind != "tpu" or not acc.tpu_slice:
            continue
        if try_parse_topology(acc.slice_topology) is None or acc.chips < 1:
            continue
        slices[acc.tpu_slice] = (acc.tpu_type, acc.slice_topology, acc.chips)
    classes: SliceClasses = {}
    for geom in slices.values():
        classes[geom] = classes.get(geom, 0) + 1
    return classes


def _accel_chip_suffix(accelerator: str) -> Optional[int]:
    tail = accelerator.rsplit("-", 1)[-1]
    return int(tail) if tail.isdigit() else None


def _safe_chips(topology: str) -> Optional[int]:
    dims = try_parse_topology(topology)
    if dims is None:
        return None
    return topology_chips(topology)


def analyze_trainjob(
    job: Optional[TrainJob],
    runtime: Optional[TrainingRuntime],
    nodes: Optional[Iterable] = None,
    podgroups: Optional[Iterable] = None,
    target: str = "",
    priority_classes: Optional[Iterable] = None,
    cluster_queues: Optional[Iterable] = None,
) -> LintReport:
    """The full static dry-run for one TrainJob against its resolved runtime.

    `nodes` (any iterable of cluster Node objects, fake or live) enables the
    inventory-dependent rules (TPU002-vs-inventory, CAP001/CAP002);
    `podgroups` enables the queue analysis (GANG001/GANG002, CAP002);
    `priority_classes`/`cluster_queues` enable the tenancy rules
    (TEN001/TEN002). Any may be None — rules that need them are skipped,
    never guessed.
    """
    report = LintReport(target=target or (job.name if job is not None else ""))
    trainer = job.trainer if job is not None else None

    if job is not None and job.metadata.name:
        name = job.metadata.name
        if not is_dns1035_label(name):
            report.add("JOB001", f"{name!r} is not a DNS-1035 label", "metadata.name")

    if job is not None and priority_classes is not None:
        pc_name = job.labels.get(PRIORITY_CLASS_LABEL, "")
        if pc_name and pc_name not in {
            c.metadata.name for c in priority_classes
        }:
            report.add(
                "TEN001",
                f"PriorityClass {pc_name!r} does not exist",
                f"labels[{PRIORITY_CLASS_LABEL}]",
            )

    if runtime is None:
        ref = job.runtime_ref if job is not None else None
        report.add(
            "RT001",
            f"runtime {ref.kind}/{ref.name} not found" if ref else "no runtime resolved",
            "runtimeRef",
        )
        return report

    ml = runtime.spec.ml_policy
    if runtime.spec.replicated_job(TRAINER_NODE) is None:
        report.add(
            "RT002",
            f"runtime {runtime.name!r} declares no {TRAINER_NODE!r} template",
            "spec.template",
        )

    # -- failure-policy / elastic-range sanity ------------------------------
    torch = ml.torch
    if torch is not None:
        lo, hi = torch.elastic_min_nodes, torch.elastic_max_nodes
        resolved_nodes = ml.num_nodes
        if trainer is not None and trainer.num_nodes is not None:
            resolved_nodes = trainer.num_nodes
        if (lo is None) != (hi is None):
            report.add("POL001", "elastic_min_nodes and elastic_max_nodes must "
                       "be set together", "mlPolicy.torch")
        elif lo is not None and hi is not None:
            if lo < 1 or lo > hi:
                report.add("POL001", f"elastic range [{lo}, {hi}] is invalid",
                           "mlPolicy.torch")
            elif not (lo <= resolved_nodes <= hi):
                report.add("POL001",
                           f"numNodes={resolved_nodes} outside elastic range [{lo}, {hi}]",
                           "mlPolicy.torch")
        if torch.max_restarts is not None and torch.max_restarts < 0:
            report.add("POL002", f"max_restarts={torch.max_restarts} is negative",
                       "mlPolicy.torch.maxRestarts")

    # -- env-bootstrap conflicts --------------------------------------------
    if trainer is not None and trainer.env:
        injected = JAX_INJECTED_ENV if ml.tpu is not None else (
            TORCH_INJECTED_ENV if torch is not None else frozenset()
        )
        clashes = sorted(set(trainer.env) & injected)
        if clashes:
            report.add(
                "ENV001",
                "user env overrides operator bootstrap vars: " + ", ".join(clashes),
                "trainer.env",
            )

    # -- TPU topology feasibility -------------------------------------------
    tpu = ml.tpu
    if tpu is None or not tpu.topology:
        return report

    chips_per_slice = _safe_chips(tpu.topology)
    if chips_per_slice is None:
        report.add("TPU001", f"unparseable topology {tpu.topology!r}",
                   "mlPolicy.tpu.topology")
        return report

    num_slices = tpu.num_slices
    base_nodes = ml.num_nodes
    if base_nodes < 1:
        # The webhook rejects this on stored runtimes, but CLI inline
        # runtimes reach the analyzer unvalidated — never divide by it.
        report.add("TPU004", f"numNodes={base_nodes} must be >= 1",
                   "mlPolicy.numNodes")
        return report
    if num_slices < 1 or base_nodes % num_slices:
        report.add(
            "TPU004",
            f"numNodes={base_nodes} not divisible into numSlices={num_slices}",
            "mlPolicy.numNodes",
        )
        return report
    per_slice = base_nodes // num_slices
    total_chips = chips_per_slice * num_slices

    if chips_per_slice % per_slice:
        report.add(
            "TPU001",
            f"{per_slice} node(s) per slice cannot tile {tpu.topology} "
            f"({chips_per_slice} chips): chips-per-host would be "
            f"{chips_per_slice / per_slice:g}",
            "mlPolicy.numNodes",
        )
        return report
    chips_per_host = chips_per_slice // per_slice

    # Explicit procPerNode must agree with the derived chips-per-host, and
    # the job-resolved node count x proc must tile whole slices (the
    # workload always places whole `chips_per_slice` blocks).
    n_resolved = base_nodes
    if trainer is not None and trainer.num_nodes is not None:
        n_resolved = trainer.num_nodes
    proc = None
    if trainer is not None and trainer.num_proc_per_node is not None:
        proc = trainer.num_proc_per_node
    if proc is not None:
        if proc != chips_per_host:
            report.add(
                "TPU001",
                f"numProcPerNode={proc} != chips-per-host {chips_per_host} "
                f"({tpu.topology} over {per_slice} node(s) per slice)",
                "trainer.numProcPerNode",
            )
        elif (n_resolved * proc) % chips_per_slice:
            report.add(
                "TPU001",
                f"numNodes={n_resolved} x numProcPerNode={proc} = "
                f"{n_resolved * proc} chips cannot tile whole {tpu.topology} "
                f"slices ({chips_per_slice} chips each)",
                "trainer.numProcPerNode",
            )

    # Contiguity: the request must admit at least one axis-aligned candidate
    # on its own slice grid — hosts owning `chips_per_host` consecutive
    # minor-axis chips must tile the grid (packer precondition).
    if host_grid_dims(tpu.topology, chips_per_host) is None or (
        not _candidates.feasible(tpu.topology, chips_per_host, tpu.topology)
    ):
        report.add(
            "TPU002",
            f"{chips_per_host}-chip hosts cannot tile {tpu.topology}: no "
            "contiguous ICI sub-mesh placement exists",
            "mlPolicy.tpu.topology",
        )

    if tpu.mesh_axes:
        prod = 1
        for v in tpu.mesh_axes.values():
            prod *= v
        if prod != total_chips:
            report.add(
                "TPU003",
                f"mesh_axes product {prod} != total chips {total_chips}",
                "mlPolicy.tpu.meshAxes",
            )

    suffix = _accel_chip_suffix(tpu.accelerator)
    if suffix is not None and suffix != chips_per_slice:
        report.add(
            "TPU005",
            f"accelerator {tpu.accelerator!r} names {suffix} chips but "
            f"topology {tpu.topology} has {chips_per_slice}",
            "mlPolicy.tpu.accelerator",
        )

    # Tenancy fit (TEN002): the gang's total chip demand against its
    # ClusterQueue's hard ceiling (quota + borrowing, tenancy/api.py
    # ClusterQueue.cap). Statically decidable from (spec, queue object) —
    # but WARN, not reject: quotas are operator-mutable cluster state.
    if job is not None and cluster_queues is not None:
        q_name = job.labels.get(QUEUE_LABEL, "")
        if q_name:
            by_name = {q.metadata.name: q for q in cluster_queues}
            queue = by_name.get(q_name)
            if queue is None:
                report.add(
                    "TEN002",
                    f"ClusterQueue {q_name!r} does not exist — the gang "
                    "waits until it is created",
                    f"labels[{QUEUE_LABEL}]",
                )
            else:
                cap = queue.cap(TPU_RESOURCE)
                if TPU_RESOURCE in queue.quota and total_chips > cap + 1e-9:
                    report.add(
                        "TEN002",
                        f"gang needs {total_chips} chips but queue "
                        f"{q_name!r} caps at {cap:g} "
                        f"(quota {queue.quota.get(TPU_RESOURCE, 0.0):g} + "
                        f"borrowing "
                        f"{queue.borrowing_limit.get(TPU_RESOURCE, 0.0):g}) "
                        "— it can never admit",
                        f"labels[{QUEUE_LABEL}]",
                    )

    # Whole-slice override discipline (plugins.WorkloadBuilderPlugin clamps).
    if trainer is not None and trainer.num_nodes is not None:
        n = trainer.num_nodes
        if n % per_slice:
            clamped = max(per_slice, (n // per_slice) * per_slice)
            report.add(
                "NODE001",
                f"numNodes override {n} is not a multiple of workers-per-slice "
                f"{per_slice}; the workload builder will clamp it to {clamped}",
                "trainer.numNodes",
            )

    # Restart budget vs host failure (NODE002): on a multi-host TPU job one
    # dead host breaks the whole slice's ICI mesh — the gang re-solves and
    # every worker restarts. Node-lost evictions themselves are budget-free
    # (engine triage), but the SURVIVING workers' own exits are not: with
    # torch maxRestarts 0 (explicit, or unset — torchrun defaults to 0) or
    # a Never trainer restart policy, those exits fail the job permanently.
    if n_resolved > 1:
        if torch is not None and (torch.max_restarts or 0) < 1:
            report.add(
                "NODE002",
                f"multi-host TPU job ({n_resolved} hosts) has "
                f"maxRestarts={'0 (torchrun default)' if torch.max_restarts is None else torch.max_restarts}"
                " — it cannot survive a single host failure",
                "mlPolicy.torch.maxRestarts",
            )
        else:
            from training_operator_tpu.api.common import RestartPolicy

            rj = runtime.spec.replicated_job(TRAINER_NODE)
            if (
                rj is not None
                and rj.template.restart_policy == RestartPolicy.NEVER
            ):
                report.add(
                    "NODE002",
                    f"multi-host TPU job ({n_resolved} hosts) with a Never "
                    "trainer restart policy — surviving workers' exits after "
                    "one host failure fail the job permanently",
                    "spec.template.restartPolicy",
                )

    # -- inventory-dependent rules ------------------------------------------
    if nodes is not None:
        classes = slice_classes_from_nodes(nodes)
        family = accel_family(tpu.accelerator)
        # The job's own PodGroup (when linting an already-created job) must
        # not count as competing demand on top of extra_chips/extra_slices.
        own = (job.namespace, job.name) if job is not None else None
        queued = None
        if podgroups is not None:
            queued = [
                pg for pg in podgroups
                if (pg.namespace, pg.name) != own
            ]
        _check_inventory(report, classes, family, tpu.topology, num_slices,
                         total_chips, nodes, queued)
    return report


def _check_inventory(
    report: LintReport,
    classes: SliceClasses,
    family: str,
    topology: str,
    num_slices: int,
    total_chips: int,
    nodes: Iterable,
    podgroups: Optional[Iterable],
) -> None:
    if not classes:
        report.add("CAP001", "inventory has no TPU slices at all",
                   "mlPolicy.tpu")
        return
    matching = {g: n for g, n in classes.items() if not family or g[0] == family}
    if not matching:
        have = sorted({g[0] for g in classes})
        report.add("CAP001",
                   f"no {family!r} slices in inventory (have: {', '.join(have)})",
                   "mlPolicy.tpu.accelerator")
        return
    feasible = sum(
        count for (t, slice_topo, cph), count in matching.items()
        if _candidates.feasible(slice_topo, cph, topology)
    )
    if feasible == 0:
        geoms = sorted({f"{g[1]}/{g[2]}chip-hosts" for g in matching})
        report.add(
            "TPU002",
            f"topology {topology} fits no slice geometry in the inventory "
            f"({', '.join(geoms)})",
            "mlPolicy.tpu.topology",
        )
        return
    if feasible < num_slices:
        report.add(
            "CAP001",
            f"request needs {num_slices} slice(s) but only {feasible} "
            f"matching slice(s) exist",
            "mlPolicy.tpu.numSlices",
        )
    if podgroups is not None:
        queue = analyze_gang_queue(
            podgroups, nodes,
            extra_chips=float(total_chips),
            extra_slices=num_slices,
        )
        report.extend(queue)


def analyze_gang_queue(
    podgroups: Iterable,
    nodes: Iterable,
    extra_chips: float = 0.0,
    extra_slices: int = 0,
    target: str = "",
) -> LintReport:
    """Capacity/deadlock analysis across queued PodGroups.

    - GANG001: a queued gang whose ICI topology fits no slice geometry will
      sit Unschedulable forever (statically decidable — flag it now).
    - GANG002: individually-placeable whole-slice gangs collectively demand
      more slices than exist; admission order decides who waits.
    - CAP002: total queued chip demand (plus `extra_chips` for a job being
      linted pre-submit) exceeds the pool's total chips.

    Both sides of the capacity comparisons span ALL accelerator families:
    PodGroups don't carry a tpu_type, so demand can't be family-filtered —
    filtering only the supply side would invent contention between disjoint
    pools. Cross-family totals under-warn at worst; never over-warn.
    """
    report = LintReport(target=target)
    classes = slice_classes_from_nodes(nodes)
    total_slices = sum(classes.values())
    total_chips = sum(
        topology_chips(topo) * n for (t, topo, _), n in classes.items()
    )
    demanded_chips = extra_chips
    demanded_slices = extra_slices
    for pg in podgroups:
        if pg.phase not in (PodGroupPhase.PENDING, PodGroupPhase.UNSCHEDULABLE):
            continue
        demanded_chips += pg.min_resources.get(TPU_RESOURCE, 0.0)
        topo = pg.topology_request
        if topo is None:
            continue
        demanded_slices += max(1, pg.num_slices)
        # topology_request is untrusted live data (PodGroups have no
        # admission hook): a malformed value is itself a never-placeable
        # gang, not an excuse to crash every subsequent lint/admission.
        if try_parse_topology(topo) is None:
            report.add(
                "GANG001",
                f"queued gang {pg.namespace}/{pg.name} requests unparseable "
                f"topology {topo!r} — it can never admit",
                f"podgroup/{pg.name}",
            )
            continue
        placeable = any(
            _candidates.feasible(slice_topo, cph, topo)
            for (_, slice_topo, cph) in classes
        )
        if not placeable:
            report.add(
                "GANG001",
                f"queued gang {pg.namespace}/{pg.name} requests {topo} which "
                "fits no slice geometry — it can never admit",
                f"podgroup/{pg.name}",
            )
    if total_chips and demanded_chips > total_chips:
        report.add(
            "CAP002",
            f"queued demand {demanded_chips:g} chips exceeds pool total "
            f"{total_chips:g}",
        )
    if total_slices and demanded_slices > total_slices:
        report.add(
            "GANG002",
            f"queued gangs want {demanded_slices} slice(s), pool has "
            f"{total_slices} — admission order decides who waits",
        )
    return report


def analyze_runtime(
    runtime: TrainingRuntime,
    nodes: Optional[Iterable] = None,
    target: str = "",
) -> LintReport:
    """Lint a runtime on its own base shape (no TrainJob overrides) — what
    `lint --preset` and the runtime-admission WARN path run."""
    return analyze_trainjob(
        None, runtime, nodes=nodes, target=target or runtime.name
    )
