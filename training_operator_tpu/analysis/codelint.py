"""Project-specific AST lint for control-plane discipline.

Rules (all ERROR; the tree must stay green — `make lint` runs this):

  CL001 sleep-in-control-loop    `time.sleep` inside the reconcile/ticker
        packages (controllers/, engine/, runtime/, scheduler/). Control
        loops must advance via the cluster clock (VirtualClock scheduling /
        schedule_after), or simulation and virtual-clock tests silently
        stall on real wall time.
  CL002 snapshot-mutation-outside-scheduler    mutating a ClusterSnapshot
        (`snap.commit(...)`, writes to `.free`/`.nodes`/`.slices`) outside
        scheduler/ — the snapshot is the solver's immutable view; outside
        writers corrupt reservation accounting.
  CL003 naked-thread    `threading.Thread(...)` without `daemon=True` and
        with no `.join(...)` in the same function: such a thread outlives
        shutdown and hangs interpreter exit.
  CL004 wire-internals-import    importing an underscore-prefixed name from
        the wire modules (`cluster.httpapi` facade or its `cluster.wire_*`
        backends) anywhere outside those modules. The round-6 split of
        httpapi.py holds only if everything else consumes the facade's
        public surface — a private import across the seam re-welds the
        modules together and breaks silently on the next internal rename.
  CL005 metric-registration-outside-metrics    calling
        `registry.counter/gauge/histogram(...)` anywhere but
        utils/metrics.py. Every metric family is declared in one file so
        the README's family table (and the registry's duplicate-
        registration guard) can't silently drift against scattered inline
        registrations.
  CL006 invariant-rule-registration-outside-invariants    calling
        `register_invariant(...)` anywhere but observe/invariants.py (the
        CL005 pattern applied to the fleet auditor's rule catalog): the
        INV001-INV006 reference table in the README holds only if every
        rule the auditor can evaluate is declared in that one module.
  CL012 host-store-outside-factory    constructing `HostStore(...)` anywhere
        but `cluster/shards.py` (the `make_store` factory seam). A bare
        HostStore bypasses the (kind, namespace) shard map: it builds an
        unsharded durability plane next to a sharded one, and the two
        journals silently disagree about which objects' history they own.
        `cluster/store.py` defines the class; `cluster/shards.py` is the
        only module allowed to instantiate it.
  CL013 attribution-cause-outside-taxonomy    minting latency-attribution
        causes outside the registered taxonomy: either a
        `register_cause(...)` call anywhere but observe/attribution.py
        (CL005/CL006 applied to the cause catalog), or a free-text cause
        string — a `{"cause": "..."}` literal whose value is not one of the
        registered cause ids. `explain` reports and per-queue attribution
        shares are only joinable/diffable across jobs while every producer
        draws from the one taxonomy table in the README.
  CL007 full-store-walk-in-scheduler    an unfiltered `.list("Pod")` /
        `.list("Node")` / `.list_refs(...)` over the Pod or Node kinds
        anywhere in scheduler/ outside snapshot.py. The incremental solver
        is O(changed) only while the solve path reads the delta-maintained
        snapshot and the informer caches; a full-store walk creeping back
        into the cycle silently regresses it to O(cluster). snapshot.py
        owns the two legal walks (the informer prime and the selfcheck/
        rebuild arm); filtered lists (namespace/label selectors) and other
        kinds are exempt.

Run: `python -m training_operator_tpu.analysis.codelint [paths...]`
(defaults to the `training_operator_tpu` package). Exit 1 on findings.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

# Packages whose loops must use the cluster clock, never the wall clock.
CONTROL_LOOP_PACKAGES = ("controllers", "engine", "runtime", "scheduler")

# Attributes whose assignment counts as snapshot mutation.
SNAPSHOT_MUTABLE_ATTRS = ("free", "nodes", "slices")

# The wire layer's module seams (CL004): the httpapi facade and the four
# modules behind it. Matched by module path suffix so both absolute imports
# and the files' own package_rel identify consistently.
WIRE_MODULES = ("httpapi", "wire_server", "wire_transport", "wire_watch",
                "wire_runtime", "wire_shards")


def _is_wire_module_path(module: str) -> bool:
    """`module` (dotted, from an ImportFrom) names one of the wire seam
    modules."""
    tail = module.rsplit(".", 1)[-1] if module else ""
    return tail in WIRE_MODULES and ("cluster" in module.split(".") or module == tail)


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule_id} {self.message}"


def _is_time_sleep(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "sleep"
        and isinstance(f.value, ast.Name)
        and f.value.id in ("time", "_time", "_t")
    )


def _looks_like_snapshot(node: ast.AST) -> bool:
    """Name heuristic: the receiver is (or holds) a ClusterSnapshot."""
    if isinstance(node, ast.Name):
        return "snapshot" in node.id.lower() or node.id.lower() in ("snap", "snp")
    if isinstance(node, ast.Attribute):
        return "snapshot" in node.attr.lower() or node.attr.lower() == "snap"
    return False


# The registry factory methods whose call outside utils/metrics.py is a
# CL005 finding.
METRIC_FACTORIES = ("counter", "gauge", "histogram", "sliding_histogram")


def _is_registry_receiver(node: ast.AST) -> bool:
    """The receiver is (or holds) a MetricsRegistry: a bare `registry`
    name, something ending in `registry`, or an attribute access like
    `metrics.registry`."""
    if isinstance(node, ast.Name):
        return node.id.lower().endswith("registry")
    if isinstance(node, ast.Attribute):
        return node.attr.lower().endswith("registry")
    return False


def _is_metric_registration(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr in METRIC_FACTORIES
        and _is_registry_receiver(f.value)
    )


# The invariant-rule registration entry point (CL006): one name, matched as
# a bare call or an attribute call (`invariants.register_invariant`).
INVARIANT_REGISTRAR = "register_invariant"


def _is_invariant_registration(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == INVARIANT_REGISTRAR
    return isinstance(f, ast.Attribute) and f.attr == INVARIANT_REGISTRAR


# The latency-attribution cause registrar (CL013): one name, matched as a
# bare call or an attribute call (`attribution.register_cause`).
CAUSE_REGISTRAR = "register_cause"

# The registered cause taxonomy (CL013). Mirrors
# observe/attribution.py's CAUSES table; tests/test_analysis.py asserts the
# two cannot drift. A `{"cause": <literal>}` outside this tuple is a
# free-text cause string.
CAUSE_TAXONOMY = (
    "quota_wait",
    "priority_wait",
    "topology_fragmentation",
    "preemption_displacement",
    "node_loss_recovery",
    "control_plane_overhead",
    "startup",
)


def _is_cause_registration(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == CAUSE_REGISTRAR
    return isinstance(f, ast.Attribute) and f.attr == CAUSE_REGISTRAR


def _free_text_cause(node: ast.Dict) -> Optional[str]:
    """The dict literal carries a `"cause"` key whose value is a string
    constant outside the registered taxonomy; returns the rogue string."""
    for key, value in zip(node.keys, node.values):
        if (
            isinstance(key, ast.Constant)
            and key.value == "cause"
            and isinstance(value, ast.Constant)
            and isinstance(value.value, str)
            and value.value not in CAUSE_TAXONOMY
        ):
            return value.value
    return None


# The store kinds whose unfiltered walk in scheduler/ is a CL007 finding:
# these are the O(cluster) populations (pods, nodes); the tiny control-plane
# kinds (PodGroup, ClusterQueue, ...) stay legal.
FULL_WALK_KINDS = ("Pod", "Node")


def _is_full_store_walk(call: ast.Call) -> bool:
    """An unfiltered `<recv>.list("Pod"|"Node")` or `.list_refs(...)` call:
    exactly one positional argument, a string literal naming a bulk kind,
    and no namespace/label-selector arguments (a filtered list is an index
    read, not a walk)."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in ("list", "list_refs")):
        return False
    if len(call.args) != 1 or call.keywords:
        return False
    arg = call.args[0]
    return isinstance(arg, ast.Constant) and arg.value in FULL_WALK_KINDS


# The durable-store construction seam (CL012): the one module allowed to
# call the HostStore constructor. Name-matched like the other rules: a bare
# `HostStore(...)` or an attribute call ending in `.HostStore(...)`.
STORE_FACTORY_MODULE = "cluster/shards.py"


def _is_host_store_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "HostStore"
    return isinstance(f, ast.Attribute) and f.attr == "HostStore"


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return isinstance(f.value, ast.Name) and f.value.id == "threading"
    return isinstance(f, ast.Name) and f.id == "Thread"


_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _scope_walk(body) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function scopes (each
    function is its own CL003 scope — a Thread belongs to exactly one)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _SCOPE_TYPES):
            continue  # a nested def is its own scope; don't descend
        stack.extend(ast.iter_child_nodes(node))


def _scopes(tree: ast.Module) -> Iterator[list]:
    """Scope bodies: the module top level, then every (nested) function."""
    yield tree.body
    for node in ast.walk(tree):
        if isinstance(node, _SCOPE_TYPES):
            yield node.body


def check_source(path: str, source: str, package_rel: Optional[str] = None) -> List[Finding]:
    """Lint one file. `package_rel` is the path relative to the package root
    (decides which package-scoped rules apply); defaults to `path`."""
    rel = (package_rel if package_rel is not None else path).replace(os.sep, "/")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "CL000", f"syntax error: {e.msg}")]
    findings: List[Finding] = []

    in_control_pkg = any(f"{pkg}/" in rel for pkg in CONTROL_LOOP_PACKAGES)
    in_scheduler = "scheduler/" in rel
    # The one scheduler file allowed to walk the store (CL007): the
    # snapshot's informer-prime + selfcheck/rebuild arms.
    in_snapshot_module = rel.endswith("scheduler/snapshot.py")
    # The one file allowed to register metric families (CL005).
    in_metrics_module = rel.endswith("utils/metrics.py")
    # The one file allowed to register invariant rules (CL006).
    in_invariants_module = rel.endswith("observe/invariants.py")
    # The one file allowed to register attribution causes (CL013).
    in_attribution_module = rel.endswith("observe/attribution.py")
    # The wire modules may import each other's internals (one subsystem,
    # four files); everyone else goes through the httpapi facade's public
    # names.
    in_wire_layer = any(
        rel.endswith(f"cluster/{m}.py") for m in WIRE_MODULES
    )
    # The one module allowed to construct HostStore (CL012).
    in_store_factory = rel.endswith(STORE_FACTORY_MODULE)

    for node in ast.walk(tree):
        if (
            isinstance(node, ast.ImportFrom)
            and not in_wire_layer
            and node.module
            and _is_wire_module_path(node.module)
        ):
            for alias in node.names:
                if alias.name.startswith("_"):
                    findings.append(Finding(
                        path, node.lineno, "CL004",
                        f"import of wire-layer internal "
                        f"{node.module}.{alias.name} outside the wire "
                        f"modules; use the cluster.httpapi facade's public "
                        f"surface",
                    ))
        if (
            isinstance(node, ast.Call)
            and not in_metrics_module
            and _is_metric_registration(node)
        ):
            findings.append(Finding(
                path, node.lineno, "CL005",
                f"metric registration (registry.{node.func.attr}) outside "
                f"utils/metrics.py; declare the family there so the "
                f"README table and duplicate-registration guard hold",
            ))
        if (
            isinstance(node, ast.Call)
            and not in_invariants_module
            and _is_invariant_registration(node)
        ):
            findings.append(Finding(
                path, node.lineno, "CL006",
                "invariant rule registration (register_invariant) outside "
                "observe/invariants.py; declare the rule there so the "
                "INV rule catalog stays one greppable list",
            ))
        if (
            isinstance(node, ast.Call)
            and not in_attribution_module
            and _is_cause_registration(node)
        ):
            findings.append(Finding(
                path, node.lineno, "CL013",
                "attribution cause registration (register_cause) outside "
                "observe/attribution.py; declare the cause there so the "
                "taxonomy table stays one greppable list",
            ))
        if isinstance(node, ast.Dict) and not in_attribution_module:
            rogue = _free_text_cause(node)
            if rogue is not None:
                findings.append(Finding(
                    path, node.lineno, "CL013",
                    f"free-text attribution cause {rogue!r}; use a cause id "
                    f"from the registered taxonomy "
                    f"(observe/attribution.py CAUSES)",
                ))
        if (
            isinstance(node, ast.Call)
            and in_scheduler
            and not in_snapshot_module
            and _is_full_store_walk(node)
        ):
            findings.append(Finding(
                path, node.lineno, "CL007",
                f"unfiltered {node.func.attr}({node.args[0].value!r}) "
                f"full-store walk inside scheduler/; the solve cycle is "
                f"O(changed) only while walks stay in snapshot.py's "
                f"prime/rebuild path",
            ))
        if (
            isinstance(node, ast.Call)
            and not in_store_factory
            and _is_host_store_ctor(node)
        ):
            findings.append(Finding(
                path, node.lineno, "CL012",
                "HostStore construction outside cluster/shards.py; go "
                "through the make_store factory so the shard map cannot "
                "be bypassed",
            ))
        if isinstance(node, ast.Call) and _is_time_sleep(node) and in_control_pkg:
            findings.append(Finding(
                path, node.lineno, "CL001",
                "time.sleep in a control-loop package; use the cluster "
                "clock (schedule_after / VirtualClock) instead",
            ))
        if not in_scheduler:
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "commit"
                and _looks_like_snapshot(node.func.value)
            ):
                findings.append(Finding(
                    path, node.lineno, "CL002",
                    "ClusterSnapshot.commit() outside scheduler/ — the "
                    "snapshot is the solver's immutable view",
                ))
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if (
                        isinstance(base, ast.Attribute)
                        and base.attr in SNAPSHOT_MUTABLE_ATTRS
                        and _looks_like_snapshot(base.value)
                    ):
                        findings.append(Finding(
                            path, node.lineno, "CL002",
                            f"write to snapshot .{base.attr} outside scheduler/",
                        ))

    for body in _scopes(tree):
        scope_nodes = list(_scope_walk(body))
        # A `.join(...)` anywhere in the same scope counts as discipline
        # (the common start-then-join pattern).
        has_join = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "join"
            for n in scope_nodes
        )
        for node in scope_nodes:
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            has_daemon = any(k.arg == "daemon" for k in node.keywords)
            if not has_daemon and not has_join:
                findings.append(Finding(
                    path, node.lineno, "CL003",
                    "threading.Thread without daemon= or a join() in the "
                    "same scope will outlive shutdown",
                ))
    return findings


def _package_rel(path: str, base: str) -> str:
    """Path relative to the training_operator_tpu package root, however the
    file was reached. Scoped rules key off directory names under the
    package (`runtime/...`); computing relative to an arbitrary argument
    (a single file, a subdirectory) would silently strip that prefix and
    turn CL001/CL002 off — or invert CL002 inside scheduler/."""
    abspath = os.path.abspath(path).replace(os.sep, "/")
    marker = "/training_operator_tpu/"
    if marker in abspath:
        return abspath.rsplit(marker, 1)[1]
    return os.path.relpath(path, base)


def check_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for root in paths:
        if os.path.isfile(root):
            files = [root]
            base = os.path.dirname(root)
        else:
            base = root
            files = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(
                    os.path.join(dirpath, f) for f in sorted(filenames)
                    if f.endswith(".py")
                )
        for f in sorted(files):
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            findings.extend(check_source(f, src, package_rel=_package_rel(f, base)))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    if not args:
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        args = [pkg_root]
    findings = check_paths(args)
    for f in findings:
        print(f.render())
    if findings:
        print(f"codelint: {len(findings)} finding(s)")
        return 1
    print("codelint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
