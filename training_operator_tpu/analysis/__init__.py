"""Static analysis: spec dry-run lint (`speclint`) + project code lint.

Two halves:

- `speclint`: a pure, side-effect-free analyzer that takes a TrainJob + its
  resolved TrainingRuntime (+ optional inventory / queued-PodGroup snapshot)
  and emits structured diagnostics — placement feasibility decided statically,
  before anything touches the cluster. Surfaced as `python -m
  training_operator_tpu lint`, `TrainingClient.lint(...)`, and non-fatal WARN
  annotations in the admission webhook path.
- `codelint`: an AST-based checker enforcing project-specific control-plane
  discipline (no `time.sleep` in reconcile/ticker loops, no ClusterSnapshot
  mutation outside the scheduler, no naked threads). Run via `make lint`.
"""

from training_operator_tpu.analysis.diagnostics import (
    Diagnostic,
    LintReport,
    RULES,
    Severity,
)
from training_operator_tpu.analysis.speclint import (
    analyze_gang_queue,
    analyze_runtime,
    analyze_trainjob,
    slice_classes_from_nodes,
)

__all__ = [
    "Diagnostic",
    "LintReport",
    "RULES",
    "Severity",
    "analyze_gang_queue",
    "analyze_runtime",
    "analyze_trainjob",
    "slice_classes_from_nodes",
]
