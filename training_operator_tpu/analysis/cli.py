"""`python -m training_operator_tpu lint` — the speclint front-end.

Targets:
  - spec files (YAML or JSON TrainJob specs, schema below)
  - `--preset NAME` / `--all-presets`: the built-in runtime catalog
  - `--inventory FILE`: a cluster inventory JSON (same schema as the
    operator's `--cluster` file) enabling the capacity rules

Spec file schema (all keys optional except one of runtimeRef/runtime):
  name: my-job
  namespace: default
  runtimeRef: {name: tpu-jax-default, kind: ClusterTrainingRuntime}
  trainer: {numNodes: 2, numProcPerNode: 4, image: ..., env: {K: V}}
  runtime:                 # inline runtime instead of a catalog ref
    numNodes: 2
    tpu: {accelerator: v5e-8, topology: 2x4, numSlices: 1,
          meshAxes: {data: 2, fsdp: 4}}
    torch: {numProcPerNode: 1, elasticMinNodes: 1, elasticMaxNodes: 4,
            maxRestarts: 3}

Exit status: 0 when no ERROR diagnostics, 1 otherwise, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple

from training_operator_tpu.analysis.diagnostics import RULES, LintReport
from training_operator_tpu.analysis.speclint import analyze_runtime, analyze_trainjob
from training_operator_tpu.api.jobs import ObjectMeta, TPUPolicy
from training_operator_tpu.runtime.api import (
    ClusterTrainingRuntime,
    MLPolicy,
    ReplicatedJobTemplate,
    RuntimeRef,
    TorchPolicy,
    Trainer,
    TrainingRuntimeSpec,
    TrainJob,
    TRAINER_NODE,
)


def _load_doc(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        import yaml
    except ImportError:
        doc = json.loads(text)
    else:
        try:
            doc = yaml.safe_load(text)
        except yaml.YAMLError as e:
            # Normalize to the load-error path (exit 2), not a traceback.
            raise ValueError(f"invalid YAML: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: top level must be a mapping")
    return doc


def _runtime_from_doc(doc: dict, name: str = "inline") -> ClusterTrainingRuntime:
    tpu = None
    if "tpu" in doc:
        t = doc["tpu"] or {}
        tpu = TPUPolicy(
            accelerator=t.get("accelerator", "v5e-8"),
            topology=t.get("topology"),
            num_slices=int(t.get("numSlices", t.get("num_slices", 1))),
            mesh_axes={k: int(v) for k, v in (t.get("meshAxes") or t.get("mesh_axes") or {}).items()},
        )
    torch = None
    if "torch" in doc:
        t = doc["torch"] or {}
        torch = TorchPolicy(
            num_proc_per_node=t.get("numProcPerNode", t.get("num_proc_per_node")),
            elastic_min_nodes=t.get("elasticMinNodes", t.get("elastic_min_nodes")),
            elastic_max_nodes=t.get("elasticMaxNodes", t.get("elastic_max_nodes")),
            max_restarts=t.get("maxRestarts", t.get("max_restarts")),
        )
    return ClusterTrainingRuntime(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=TrainingRuntimeSpec(
            ml_policy=MLPolicy(
                num_nodes=int(doc.get("numNodes", doc.get("num_nodes", 1))),
                tpu=tpu,
                torch=torch,
            ),
            template=[ReplicatedJobTemplate(name=TRAINER_NODE)],
        ),
    )


def load_spec(path: str) -> Tuple[TrainJob, Optional[ClusterTrainingRuntime]]:
    """Parse a spec file into (TrainJob, resolved-or-None runtime)."""
    doc = _load_doc(path)
    ref = doc.get("runtimeRef") or {}
    trainer_doc = doc.get("trainer")
    trainer = None
    if trainer_doc:
        trainer = Trainer(
            image=trainer_doc.get("image"),
            command=list(trainer_doc.get("command", [])),
            args=list(trainer_doc.get("args", [])),
            env={k: str(v) for k, v in (trainer_doc.get("env") or {}).items()},
            num_nodes=trainer_doc.get("numNodes", trainer_doc.get("num_nodes")),
            num_proc_per_node=trainer_doc.get(
                "numProcPerNode", trainer_doc.get("num_proc_per_node")
            ),
            resources_per_node=dict(trainer_doc.get("resourcesPerNode", {})),
        )
    job = TrainJob(
        metadata=ObjectMeta(
            name=doc.get("name", "lint-target"),
            namespace=doc.get("namespace", "default"),
        ),
        runtime_ref=RuntimeRef(
            name=ref.get("name", ""),
            kind=ref.get("kind", ClusterTrainingRuntime.KIND),
        ),
        trainer=trainer,
    )
    if "runtime" in doc:
        return job, _runtime_from_doc(doc["runtime"] or {})
    if ref.get("name"):
        from training_operator_tpu.runtime.presets import builtin_runtimes

        for rt in builtin_runtimes():
            if rt.metadata.name == ref["name"]:
                return job, rt
    return job, None


def load_inventory(path: str) -> list:
    """Build a fake node inventory from the operator's cluster-file schema."""
    from training_operator_tpu.cluster.inventory import (
        make_cpu_pool,
        make_gpu_pool,
        make_tpu_pool,
    )

    with open(path) as f:
        inv = json.load(f)
    nodes: list = []
    for i, pool in enumerate(inv.get("tpu_pools", [])):
        nodes.extend(make_tpu_pool(
            pool.get("slices", 1),
            slice_topology=pool.get("topology", "4x4"),
            chips_per_host=pool.get("chips_per_host", 4),
            tpu_type=pool.get("tpu_type", "v5e"),
            slice_prefix=f"pool{i}-slice",
        ))
    for pool in inv.get("gpu_pools", []):
        nodes.extend(make_gpu_pool(
            pool.get("nodes", 1),
            gpus_per_node=pool.get("gpus_per_node", 8),
        ))
    for pool in inv.get("cpu_pools", []):
        nodes.extend(make_cpu_pool(pool.get("nodes", 1)))
    return nodes


def _print_rules() -> None:
    wid = max(len(r.slug) for r in RULES.values())
    for r in RULES.values():
        print(f"{r.rule_id}  {r.slug:<{wid}}  {r.severity.value:<5}  {r.catches}")


def run(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m training_operator_tpu lint",
        description="static dry-run analysis of TrainJob specs",
    )
    ap.add_argument("specs", nargs="*", help="TrainJob spec files (YAML/JSON)")
    ap.add_argument("--preset", action="append", default=[],
                    help="lint a built-in runtime preset by name (repeatable)")
    ap.add_argument("--all-presets", action="store_true",
                    help="lint every built-in preset")
    ap.add_argument("--inventory", metavar="FILE",
                    help="cluster inventory JSON enabling capacity rules")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="emit diagnostics as JSON")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only print targets with diagnostics")
    args = ap.parse_args(argv)

    if args.rules:
        _print_rules()
        return 0

    nodes = load_inventory(args.inventory) if args.inventory else None

    from training_operator_tpu.runtime.presets import builtin_runtimes

    catalog = {rt.metadata.name: rt for rt in builtin_runtimes()}
    preset_names = list(catalog) if args.all_presets else list(args.preset)

    if not args.specs and not preset_names:
        ap.print_usage(sys.stderr)
        print("error: nothing to lint (give spec files, --preset, or "
              "--all-presets)", file=sys.stderr)
        return 2

    reports: List[LintReport] = []
    for name in preset_names:
        rt = catalog.get(name)
        if rt is None:
            bad = LintReport(target=name)
            bad.add("RT001", f"no built-in preset named {name!r} "
                    f"(have: {', '.join(sorted(catalog))})", "preset")
            reports.append(bad)
            continue
        reports.append(analyze_runtime(rt, nodes=nodes, target=name))
    for path in args.specs:
        try:
            job, runtime = load_spec(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot load {path}: {e}", file=sys.stderr)
            return 2
        reports.append(
            analyze_trainjob(job, runtime, nodes=nodes, target=path)
        )

    n_errors = sum(len(r.errors()) for r in reports)
    if args.as_json:
        print(json.dumps([
            {
                "target": r.target,
                "diagnostics": [
                    {"rule": d.rule_id, "slug": d.slug,
                     "severity": d.severity.value, "path": d.path,
                     "message": d.message}
                    for d in r.diagnostics
                ],
            }
            for r in reports
        ], indent=2))
    else:
        for r in reports:
            if args.quiet and not r.diagnostics:
                continue
            print(r.render())
        total = sum(len(r.diagnostics) for r in reports)
        n_warn = sum(len(r.warnings()) for r in reports)
        print(f"lint: {len(reports)} target(s), {n_errors} error(s), "
              f"{n_warn} warning(s), {total} diagnostic(s)")
    return 1 if n_errors else 0


if __name__ == "__main__":
    sys.exit(run())
