"""Soak workload: a sustained, heavy-tailed, mixed-kind arrival process.

Everything so far benched this operator with single bursts; real fleets see
a *process*: jobs arriving continuously for days, with Pareto-tailed
durations (most jobs are minutes, a few are many hours — the README
tail-physics analysis), across every workload kind the stack serves, into
ClusterQueues whose quotas are deliberately oversubscribed (PR 8's
contention shape). This module turns one seed into that process as a
deterministic, replayable *trace*: `build_arrival_trace` is a pure function
of (seed, config), so two soak runs from the same seed submit byte-identical
workloads at identical instants — the foundation of the soak's replay pin.

Kinds in the mix (weights in `KIND_WEIGHTS`):

  jax-sub     2x4 sub-slice JAX gang (2 hosts)          team queue
  jax-host    1x4 single-host JAX gang                  team queue
  jax-full    4x4 whole-slice JAX gang (4 hosts)        team queue
  jax-multi   2-slice 4x4 multi-slice JAX gang (8 hosts) team queue
  prod        4x4 whole-slice, high priority             prod queue
  elastic     elastic PyTorchJob on the CPU pool (HPA-resizable)
  mpi         MPIJob launcher + workers on the CPU pool
  cpu         TFJob on the CPU pool
  v2          v2 TrainJob -> per-job TrainingRuntime -> 2x4 JAX gang

Every job carries `ttl_seconds_after_finished`, so terminal jobs (and their
pods, via cascade GC) leave the store — without it a week of fleet life
grows the object store linearly, which is exactly the accumulator class
INV009 exists to catch.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import (
    Container,
    PodTemplateSpec,
    ReplicaSpec,
    RunPolicy,
    SchedulingPolicy,
)
from training_operator_tpu.api.jobs import (
    ElasticPolicy,
    JAXJob,
    MPIJob,
    ObjectMeta,
    PyTorchJob,
    TFJob,
    TPUPolicy,
)
from training_operator_tpu.cluster.inventory import TPU_RESOURCE
from training_operator_tpu.cluster.runtime import ANNOTATION_SIM_DURATION

# Heavy-tailed duration physics: Pareto(alpha) scaled to x_m, truncated so
# one astronomically unlucky draw cannot outlive the whole soak. alpha=1.6
# gives a finite mean (~2.7 x_m) with a serious tail (p99 ~ 18 x_m).
PARETO_ALPHA = 1.6
DURATION_XM_S = 180.0
DURATION_CAP_S = 24 * 3600.0

# Team queues submit the bulk of the load; "prod" carries the high-priority
# wave class. Quotas are sized by the harness to oversubscribe each team
# ~2-3x at the configured arrival rate.
TEAM_QUEUES = ("team-a", "team-b", "team-c", "team-d")
PROD_QUEUE = "prod"

KIND_WEIGHTS = (
    ("jax-sub", 0.26),
    ("jax-host", 0.18),
    ("jax-full", 0.12),
    ("jax-multi", 0.05),
    ("prod", 0.07),
    ("elastic", 0.07),
    ("mpi", 0.07),
    ("cpu", 0.10),
    ("v2", 0.08),
)


@dataclass
class Arrival:
    """One scheduled submission: everything needed to build the job is
    fixed at trace time, so the trace IS the workload."""

    t: float
    kind: str
    name: str
    duration: float
    queue: str
    priority: str
    # Sharded-operator soaks spread arrivals across namespaces (reconcile
    # ownership partitions by namespace hash); the default single-namespace
    # shape is byte-identical to the pre-shard trace.
    namespace: str = "default"

    def key(self) -> tuple:
        return (round(self.t, 6), self.kind, self.name,
                round(self.duration, 6), self.queue, self.priority,
                self.namespace)


@dataclass
class SoakTrace:
    arrivals: List[Arrival] = field(default_factory=list)

    def due(self, now: float, cursor: int) -> List[Arrival]:
        out = []
        while cursor < len(self.arrivals) and self.arrivals[cursor].t <= now:
            out.append(self.arrivals[cursor])
            cursor += 1
        return out

    def log(self) -> List[tuple]:
        """The replay pin: a value-comparable view of the whole trace."""
        return [a.key() for a in self.arrivals]


def _pick_kind(rng: random.Random) -> str:
    r = rng.random()
    acc = 0.0
    for kind, w in KIND_WEIGHTS:
        acc += w
        if r < acc:
            return kind
    return KIND_WEIGHTS[-1][0]


def build_arrival_trace(
    seed: int,
    sim_seconds: float,
    arrival_per_minute: float,
    compression: float = 1.0,
    namespaces: int = 1,
) -> SoakTrace:
    """Poisson arrivals at `arrival_per_minute` over `sim_seconds`, each
    with a truncated-Pareto duration divided by `compression`. Pure
    function of its arguments — the replay test depends on it.
    `namespaces` > 1 round-robins arrivals across `soak-ns-{k}` namespaces
    (deterministically, by arrival index) so sharded-operator soaks load
    every reconcile shard; 1 keeps the single-namespace default."""
    rng = random.Random(seed)
    rate = arrival_per_minute / 60.0
    trace = SoakTrace()
    # Tail cap relative to the soak horizon: a Pareto draw several times
    # the whole run would make drain-phase convergence structurally
    # impossible (a 24h job in a compressed-hour smoke can never finish) —
    # a week-shaped run keeps the full 24h tail.
    cap = min(DURATION_CAP_S / compression, sim_seconds * 0.25)
    t = 0.0
    i = 0
    while True:
        t += rng.expovariate(rate)
        if t >= sim_seconds:
            break
        kind = _pick_kind(rng)
        dur = DURATION_XM_S * rng.paretovariate(PARETO_ALPHA) / compression
        dur = max(1.0, min(cap, dur))
        if kind == "prod":
            queue, priority = PROD_QUEUE, "high"
            # Prod waves are deadline-shaped: shorter, never tail-deep.
            dur = min(dur, 1800.0 / compression)
        elif kind in ("elastic", "mpi", "cpu"):
            queue, priority = "", "batch"  # CPU pool: unquota'd, low tier
        else:
            queue = TEAM_QUEUES[rng.randrange(len(TEAM_QUEUES))]
            priority = "normal" if rng.random() < 0.85 else "batch"
        trace.arrivals.append(Arrival(
            t=t, kind=kind, name=f"soak-{kind}-{i:05d}", duration=dur,
            queue=queue, priority=priority,
            namespace=(
                "default" if namespaces <= 1 else f"soak-ns-{i % namespaces}"
            ),
        ))
        i += 1
    return trace


# ---------------------------------------------------------------------------
# Job construction
# ---------------------------------------------------------------------------


def _tpu_template(duration: float, cpu: float = 1.0) -> PodTemplateSpec:
    return PodTemplateSpec(
        containers=[Container(
            name="jax", image="soak-trainer",
            resources={"cpu": cpu, TPU_RESOURCE: 4.0},
        )],
        annotations={ANNOTATION_SIM_DURATION: f"{duration:g}"},
    )


def _cpu_template(duration: float, cpu: float = 1.0,
                  name: str = "worker") -> PodTemplateSpec:
    return PodTemplateSpec(
        containers=[Container(
            name=name, image="soak-worker", resources={"cpu": cpu},
        )],
        annotations={ANNOTATION_SIM_DURATION: f"{duration:g}"},
    )


def _run_policy(arrival: Arrival, ttl: int) -> RunPolicy:
    return RunPolicy(
        ttl_seconds_after_finished=ttl,
        scheduling_policy=SchedulingPolicy(
            queue=arrival.queue, priority_class=arrival.priority,
        ),
    )


def build_v1_job(arrival: Arrival, ttl: int):
    """The v1 arm of the mix; returns a submit-ready job object."""
    a = arrival
    if a.kind in ("jax-sub", "jax-host", "jax-full", "jax-multi", "prod"):
        topo, workers, slices = {
            "jax-sub": ("2x4", 2, 1),
            "jax-host": ("1x4", 1, 1),
            "jax-full": ("4x4", 4, 1),
            "jax-multi": ("4x4", 8, 2),
            "prod": ("4x4", 4, 1),
        }[a.kind]
        chips = 4 * workers
        return JAXJob(
            metadata=ObjectMeta(name=a.name, namespace=a.namespace),
            replica_specs={"Worker": ReplicaSpec(
                replicas=workers, template=_tpu_template(a.duration),
                restart_policy=capi.RestartPolicy.EXIT_CODE,
            )},
            tpu_policy=TPUPolicy(
                accelerator=f"v5e-{chips // max(1, slices)}", topology=topo,
                num_slices=slices,
            ),
            run_policy=_run_policy(a, ttl),
        )
    if a.kind == "elastic":
        return PyTorchJob(
            metadata=ObjectMeta(name=a.name, namespace=a.namespace),
            replica_specs={"Worker": ReplicaSpec(
                replicas=2, template=_cpu_template(a.duration, name="pytorch"),
                restart_policy=capi.RestartPolicy.EXIT_CODE,
            )},
            elastic_policy=ElasticPolicy(min_replicas=1, max_replicas=4),
            run_policy=_run_policy(a, ttl),
        )
    if a.kind == "mpi":
        return MPIJob(
            metadata=ObjectMeta(name=a.name, namespace=a.namespace),
            replica_specs={
                "Launcher": ReplicaSpec(
                    replicas=1,
                    template=_cpu_template(a.duration, cpu=0.5, name="mpi"),
                    restart_policy=capi.RestartPolicy.EXIT_CODE,
                ),
                "Worker": ReplicaSpec(
                    replicas=2,
                    template=_cpu_template(a.duration, name="mpi"),
                    restart_policy=capi.RestartPolicy.EXIT_CODE,
                ),
            },
            slots_per_worker=2,
            run_policy=_run_policy(a, ttl),
        )
    if a.kind == "cpu":
        return TFJob(
            metadata=ObjectMeta(name=a.name, namespace=a.namespace),
            replica_specs={"Worker": ReplicaSpec(
                replicas=2, template=_cpu_template(a.duration, name="tensorflow"),
                restart_policy=capi.RestartPolicy.EXIT_CODE,
            )},
            run_policy=_run_policy(a, ttl),
        )
    raise ValueError(f"not a v1 arrival kind: {a.kind!r}")


def build_v2_job(arrival: Arrival):
    """The v2 arm: a per-job namespaced TrainingRuntime carrying this job's
    sim duration (pod annotations come from the runtime's pod template, so
    per-job durations need per-job runtimes) plus the TrainJob referencing
    it. Tenancy routes via the TrainJob's labels (QUEUE_LABEL /
    PRIORITY_CLASS_LABEL, the kueue queue-name-label pattern). Returns
    (runtime, trainjob); the harness's janitor deletes both once the
    TrainJob is terminal (TrainJobs have no TTL field — the janitor plays
    the user's cleanup-controller role)."""
    from training_operator_tpu.runtime import MLPolicy, TrainJob
    from training_operator_tpu.runtime.api import (
        CoschedulingPolicy,
        PodGroupPolicy,
        ReplicatedJobTemplate,
        RuntimeRef,
        TrainingRuntime,
        TrainingRuntimeSpec,
        TRAINER_NODE,
    )
    from training_operator_tpu.tenancy.api import (
        PRIORITY_CLASS_LABEL,
        QUEUE_LABEL,
    )

    a = arrival
    runtime = TrainingRuntime(
        metadata=ObjectMeta(name=f"{a.name}-rt", namespace=a.namespace),
        spec=TrainingRuntimeSpec(
            ml_policy=MLPolicy(
                num_nodes=2,
                tpu=TPUPolicy(accelerator="v5e-8", topology="2x4",
                              mesh_axes={"data": 2, "fsdp": 4}),
            ),
            pod_group_policy=PodGroupPolicy(coscheduling=CoschedulingPolicy()),
            template=[ReplicatedJobTemplate(
                name=TRAINER_NODE, replicas=2,
                template=_tpu_template(a.duration, cpu=0.5),
            )],
        ),
    )
    job = TrainJob(
        metadata=ObjectMeta(name=a.name, namespace=a.namespace),
        runtime_ref=RuntimeRef(kind=TrainingRuntime.KIND, name=f"{a.name}-rt"),
        labels={QUEUE_LABEL: a.queue, PRIORITY_CLASS_LABEL: a.priority},
    )
    return runtime, job


def tenancy_objects(team_quota_chips: float, prod_quota_chips: float):
    """The queue/priority catalog the soak submits into: four team queues
    with equal chip quotas (borrowing up to one extra quota each) plus the
    prod queue, and the three priority tiers."""
    from training_operator_tpu.tenancy import ClusterQueue, PriorityClass

    objs: List[object] = [
        PriorityClass(metadata=ObjectMeta(name="high"), value=1000),
        PriorityClass(metadata=ObjectMeta(name="normal"), value=500,
                      global_default=True),
        PriorityClass(metadata=ObjectMeta(name="batch"), value=100),
    ]
    for team in TEAM_QUEUES:
        objs.append(ClusterQueue(
            metadata=ObjectMeta(name=team),
            quota={TPU_RESOURCE: team_quota_chips},
            borrowing_limit={TPU_RESOURCE: team_quota_chips},
        ))
    objs.append(ClusterQueue(
        metadata=ObjectMeta(name=PROD_QUEUE),
        quota={TPU_RESOURCE: prod_quota_chips},
    ))
    return objs
