"""Time-compressed fleet soak: simulated days of fleet life in minutes.

Every number this repo published so far came from a single burst on a toy
topology. The soak harness runs the FULL stack — durable host store, wire
fault boundary, operator manager (v1 + v2), incremental gang solver,
tenancy arbiter, node lifecycle, WAL replication — through a sustained
heavy-tailed arrival process on a 10k-node topology, with all five chaos
tiers live simultaneously — plus, for sharded multi-replica runs, a sixth
disruption class that SIGKILLs one operator replica mid-soak — and the
fail-fast invariant auditor (INV001–INV010) as the standing oracle: any
invariant violation halts the run with a replayable seed.

Time compression: `compression` C maps fleet time onto sim time — job
durations, arrival gaps, and every control cadence are divided by C, and
all reported numbers (SLOs, MTTR, throughput) are scaled back to fleet
seconds. A simulated week at C=4 runs 42 sim-hours of virtual clock; the
virtual clock itself skips idle time, so wall cost scales with *events*,
not with simulated seconds.

Five tiers, one seed (soak/orchestrator.py):

  pod    ChaosMonkey kills through the kubelet exit path
  api    APIChaos conflicts + drop/dup on the operator's watch queues
  wire   WireChaos error/reset decisions applied at the IN-PROCESS wire
         boundary (`WireFacade`): the operator manager's API verbs raise
         ApiServerError/ApiUnavailableError exactly where the remote
         deployment's transport would, and heal through the same arms —
         reconcile requeue+backoff, resync, expectations unwind
  node   NodeChaos host/slice kills + rolling maintenance windows
  host   mid-soak control-plane death: the primary HostStore is abandoned
         (HostChaos SIGKILL semantics), the in-process warm standby —
         which tailed the WAL in seq lockstep all along — drains the
         reachable tail, verifies byte-level parity, and is promoted to
         run the rest of the soak

The harness is single-threaded and fully deterministic: same seed, same
config → identical arrival trace, kill logs, and final state (the replay
test pins this).
"""

from __future__ import annotations

import dataclasses
import logging
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import JobConditionType
from training_operator_tpu.cluster.chaos import HostChaos
from training_operator_tpu.cluster.httpapi import (
    ApiServerError,
    ApiUnavailableError,
)
from training_operator_tpu.cluster.inventory import (
    make_cpu_pool,
    make_tpu_pool,
)
from training_operator_tpu.cluster.runtime import Cluster, VirtualClock
from training_operator_tpu.cluster.shards import StoreShardSet, make_store
from training_operator_tpu.cluster.store import HostStore
from training_operator_tpu.config import OperatorConfig, parse_chaos_intensity
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.observe.invariants import (
    RULES,
    FleetSources,
    InvariantAuditor,
)
from training_operator_tpu.soak import workload as wl
from training_operator_tpu.soak.orchestrator import ChaosOrchestrator
from training_operator_tpu.utils import locks, metrics

log = logging.getLogger(__name__)

WATCHED_KINDS = ("JAXJob", "PyTorchJob", "TFJob", "MPIJob", "TrainJob")


@dataclass
class SoakConfig:
    """All knobs in FLEET seconds/rates; `compression` maps them to sim.

    Defaults are the bench-soak shape: a simulated week on 10k TPU hosts.
    Control cadences are deliberately scaled-up from the interactive
    defaults (heartbeats every 10s at 10k nodes over a week would be 600M
    lease writes — the cadence scales with the compression of fleet time,
    exactly like SLO windows do)."""

    sim_hours: float = 168.0
    arrival_per_minute: float = 2.0
    compression: float = 4.0
    chaos: Dict[str, float] = field(
        default_factory=lambda: {t: 1.0 for t in
                                 ("pod", "api", "wire", "node", "host")})
    seed: int = 14
    # Topology: tpu_slices*4 TPU hosts + cpu_nodes CPU hosts.
    tpu_slices: int = 2500
    slice_topology: str = "4x4"
    cpu_nodes: int = 64
    cpu_per_node: float = 32.0
    # Fleet-seconds control cadences (divided by compression for sim).
    epoch_seconds: float = 3600.0
    heartbeat_seconds: float = 3600.0
    grace_seconds: float = 7500.0
    toleration_seconds: float = 1800.0
    # Reboot-class node outage length: longer than detect+evict
    # (grace + heartbeat + toleration) so node deaths produce REAL
    # recovery arcs (evict -> re-solve -> Running) and MTTR samples,
    # instead of being silently absorbed by the grace window.
    recover_seconds: float = 4 * 3600.0
    audit_seconds: float = 7200.0
    resync_seconds: float = 7200.0
    resolve_seconds: float = 1200.0
    min_solve_seconds: float = 240.0
    job_ttl_seconds: float = 7200.0
    compact_check_seconds: float = 240.0
    drain_hours: float = 30.0  # post-arrival convergence budget
    # Tenancy: quotas sized so the Pareto TAIL oversubscribes them (a few
    # day-long whole-slice jobs pin a team's nominal quota, borrowing and
    # preemption engage) while the steady state stays stable — nominal
    # team capacity ~= mean demand at the default arrival rate, headroom
    # only through borrowing. Contention lives at the queue, not the
    # 40k-chip pool.
    team_quota_chips: float = 32.0
    prod_quota_chips: float = 64.0
    # Storage bounds (the INV005/INV009 contract under sustained load).
    compact_every_records: int = 200_000
    compact_max_journal_bytes: int = 256 * 1024 * 1024
    replication_wal_ring: int = 131_072
    event_cap: int = 16384
    workqueue_bound: int = 50_000
    # SLO targets (fleet seconds; time-to-running = submit -> first
    # Running). The normal tier waits on oversubscribed quotas by design —
    # p50 absorbs the queue; the high-priority tier must cut through it.
    slo_p50_ttr_s: float = 7200.0
    slo_p99_ttr_s: float = 48 * 3600.0
    slo_high_p99_ttr_s: float = 6 * 3600.0
    # Operator scale-out: run this many sharded operator replicas (v1
    # manager + v2 manager pairs) over the same control plane, with
    # reconcile ownership partitioned across `operator_replicas` shard
    # leases. 1 (default) keeps the single-manager shape byte-identical to
    # the pre-shard soak. With > 1, the orchestrator schedules one mid-soak
    # replica kill (the sixth disruption class, HostChaos-style SIGKILL
    # semantics: ticker + watch detached, leases left to expire) and
    # arrivals spread across `namespaces` namespaces so every shard
    # carries load; INV010 audits the ownership contract live.
    operator_replicas: int = 1
    shard_grace_seconds: float = 600.0  # fleet seconds (sim via sim())
    namespaces: int = 1
    # Sharded write plane: partition the durable store into this many
    # write shards (cluster/shards.py StoreShardSet), each with its own
    # journal, WAL ring, and VirtualStandby in seq lockstep. 1 (default)
    # keeps the single-store soak byte-identical to the pre-shard shape
    # (the replay pin). With > 1 the host-chaos failover signal becomes a
    # PER-SHARD failover: one shard's store is abandoned and its standby's
    # store adopted, the other shards' journals undisturbed; INV011 audits
    # key ownership across shards the whole week.
    store_shards: int = 1
    # Safety rails.
    max_wall_seconds: float = 3600.0
    failovers: Optional[int] = None  # None = 1 iff chaos host tier > 0

    @classmethod
    def from_operator_config(cls, cfg: OperatorConfig, **overrides) -> "SoakConfig":
        base = cls(
            sim_hours=cfg.soak_hours,
            arrival_per_minute=cfg.soak_arrival_per_minute,
            compression=cfg.soak_compression,
            chaos=parse_chaos_intensity(cfg.soak_chaos),
            seed=cfg.soak_seed,
        )
        return dataclasses.replace(base, **overrides)

    def sim(self, fleet_seconds: float) -> float:
        return fleet_seconds / self.compression

    def fleet(self, sim_seconds: float) -> float:
        return sim_seconds * self.compression

    @property
    def sim_seconds(self) -> float:
        return self.sim(self.sim_hours * 3600.0)


class SoakError(RuntimeError):
    """The soak could not complete (wall budget, non-convergence, ...)."""


# ---------------------------------------------------------------------------
# The in-process wire boundary (tier 3)
# ---------------------------------------------------------------------------


class _FaultingAPI:
    """Proxy over one APIServer that injects wire-tier faults on the verbs
    that cross the wire in the remote deployment. Reads and writes both
    fault (a 500 mid-GET is as real as one mid-POST); watch delivery does
    not — that is the api tier's jurisdiction (APIChaos drop/dup)."""

    _FAULTED = ("create", "update", "delete", "try_delete", "get",
                "try_get", "list", "list_refs")

    def __init__(self, api, chaos):
        self._api = api
        self._chaos = chaos
        # Gated off during stack construction: a booting operator retries
        # its way through a storm (the chaos-matrix tests prove that arm);
        # the soak's wire tier targets the STEADY state, and a half-built
        # manager retrying construction would duplicate registrations.
        self.enabled = True
        for verb in self._FAULTED:
            setattr(self, verb, self._wrap(getattr(api, verb)))

    def _wrap(self, fn):
        def gated(*args, **kwargs):
            if self.enabled:
                decision = self._chaos.sample()
                if decision == "error":
                    metrics.soak_wire_faults.inc("error")
                    raise ApiServerError("soak wire chaos: injected 500")
                if decision == "reset":
                    metrics.soak_wire_faults.inc("reset")
                    raise ApiUnavailableError(
                        "soak wire chaos: connection reset")
            return fn(*args, **kwargs)

        return gated

    def __getattr__(self, name):
        return getattr(self._api, name)


class WireFacade:
    """A Cluster-shaped view handed to the operator managers: same clock
    and timer surface, but `api` faults like a flaky transport and tickers
    get the RemoteRuntime.run_forever retry arm — a transport error aborts
    the remainder of this tick and the next tick retries, instead of
    crashing the whole step loop."""

    def __init__(self, cluster: Cluster, chaos):
        self._cluster = cluster
        self.api = _FaultingAPI(cluster.api, chaos)
        self.clock = cluster.clock
        self._wrapped: Dict[Any, Any] = {}
        self.tick_aborts = 0

    def add_ticker(self, fn) -> None:
        def guarded():
            try:
                fn()
            except (ApiServerError, ApiUnavailableError):
                self.tick_aborts += 1
                metrics.soak_wire_faults.inc("tick_abort")

        self._wrapped[fn] = guarded
        self._cluster.add_ticker(guarded)

    def remove_ticker(self, fn) -> None:
        self._cluster.remove_ticker(self._wrapped.pop(fn, fn))

    def schedule_at(self, t, fn) -> None:
        self._cluster.schedule_at(t, fn)

    def schedule_after(self, dt, fn) -> None:
        self._cluster.schedule_after(dt, fn)

    @property
    def kubelet(self):
        return self._cluster.kubelet

    @property
    def informer(self):
        return self._cluster.informer


# ---------------------------------------------------------------------------
# In-process warm standby (tier 5's other half)
# ---------------------------------------------------------------------------


class VirtualStandby:
    """The StandbyController's ingest path on the virtual clock: tails the
    primary store's WAL ring directly (no HTTP — the soak is one process)
    and applies records via APIServer.apply_replicated in seq lockstep,
    journaling to its OWN HostStore so the promoted incarnation is durable
    in its own right. Both stores start empty at t=0, so the tail from seq
    0 keeps the stores byte-identical — verified at failover."""

    def __init__(self, clock, primary_store: HostStore, state_dir: str,
                 cfg: SoakConfig):
        self.cluster = Cluster(clock)
        self.primary_store = primary_store
        self.store = make_store(
            state_dir,
            compact_every=cfg.compact_every_records,
            compact_max_bytes=cfg.compact_max_journal_bytes,
            wal_ring=cfg.replication_wal_ring,
        )
        self.store.load_into(self.cluster.api)
        self.store.attach(self.cluster.api)
        self.cluster.api.set_event_cap(cfg.event_cap)
        self.cursor = 0
        self.applied = 0
        self.lag_records = 0
        self.promoted = False

    def pump(self, limit: int = 100_000) -> int:
        """Apply every shipped record up to the primary's WAL head."""
        applied = 0
        while True:
            page = self.primary_store.wal_page(
                after=self.cursor, limit=4096, timeout=0.0)
            if page.get("reset"):
                raise SoakError(
                    "standby outran the WAL ring mid-soak — "
                    "replication_wal_ring is undersized for the write rate"
                )
            records = page.get("records", [])
            for rec in records:
                self.cluster.api.apply_replicated(rec["r"])
                self.cursor = int(rec["s"])
                applied += 1
            self.lag_records = max(0, int(page.get("head", 0)) - self.cursor)
            if not records or applied >= limit:
                break
        self.applied += applied
        if applied:
            metrics.replication_records_applied.inc(amount=applied)
        return applied

    def lag(self) -> Dict[str, Any]:
        """StandbyController.lag() shape — feeds INV008 on the auditor."""
        return {
            "role": "primary" if self.promoted else "standby",
            "records": self.lag_records,
            "seconds": 0.0 if self.lag_records == 0 else 1e9,
            "connected": True,
            "applied": self.applied,
        }


# ---------------------------------------------------------------------------
# Lifecycle tracking
# ---------------------------------------------------------------------------


@dataclass
class JobRecord:
    kind: str
    queue: str
    priority: str
    submitted: float  # sim time
    namespace: str = "default"
    running: Optional[float] = None      # first Running (sim)
    last_running: Optional[float] = None  # latest Running transition (sim)
    finished: Optional[float] = None
    succeeded: bool = False


@dataclass
class Disruption:
    tier: str
    job: str
    t_open: float  # sim
    t_close: Optional[float] = None
    outcome: str = ""  # recovered | completed | failed | absorbed | open


class JobTracker:
    """Watch-fed lifecycle table for every soak-submitted job. v2 jobs
    appear twice in the event stream — the TrainJob and its same-named v1
    workload — so Running comes from whichever carries the condition and
    terminal state prefers the TrainJob."""

    def __init__(self, api):
        self.jobs: Dict[str, JobRecord] = {}
        self.transitions: List[Tuple[str, str, float]] = []  # drained per loop
        self.gc_unobserved = 0
        self._watch = None
        self.rebind(api)

    def rebind(self, api) -> None:
        """Point at a (newly promoted) APIServer: fresh watch + one full
        reconcile pass so transitions written during the switch are not
        lost."""
        if self._watch is not None:
            try:
                self._api.unwatch(self._watch)
            except Exception:  # noqa: BLE001 — the old api may be dead
                pass
        self._api = api
        self._watch = api.watch(kinds=WATCHED_KINDS)
        for kind in WATCHED_KINDS:
            for obj in api.list(kind):
                self._observe(kind, obj, deleted=False)

    def track(self, name: str, kind: str, queue: str, priority: str,
              submitted: float, namespace: str = "default") -> None:
        self.jobs[name] = JobRecord(kind, queue, priority, submitted,
                                    namespace=namespace)

    def _observe(self, kind: str, obj, deleted: bool,
                 now: float = 0.0) -> None:
        name = obj.metadata.name
        rec = self.jobs.get(name)
        if rec is None:
            return
        if deleted:
            if rec.finished is None:
                # TTL GC only deletes finished jobs; if the terminal write
                # was never observed (lost across a failover switch), close
                # the record at the delete instant and count the gap.
                if kind != "TrainJob" and rec.kind == "v2":
                    return  # workload GC'd by janitor; TrainJob decides
                rec.finished = now
                self.gc_unobserved += 1
                self.transitions.append((name, "terminal", rec.finished))
            return
        if kind == "TrainJob":
            from training_operator_tpu.runtime.api import TrainJobConditionType

            complete = obj.condition(TrainJobConditionType.COMPLETE)
            failed = obj.condition(TrainJobConditionType.FAILED)
            if rec.finished is None:
                if complete is not None and complete.status:
                    rec.finished = complete.last_transition_time
                    rec.succeeded = True
                elif failed is not None and failed.status:
                    rec.finished = failed.last_transition_time
                if rec.finished is not None:
                    self.transitions.append((name, "terminal", rec.finished))
            return
        cond = capi.get_condition(obj.status, JobConditionType.RUNNING)
        if cond is not None and cond.status:
            t = cond.last_transition_time
            if rec.running is None:
                rec.running = t
                self.transitions.append((name, "running", t))
            elif rec.last_running is None or t > rec.last_running:
                self.transitions.append((name, "running", t))
            rec.last_running = t
        if rec.kind != "v2" and rec.finished is None and capi.is_finished(obj.status):
            rec.finished = (
                obj.status.completion_time
                if obj.status.completion_time is not None
                else cond.last_transition_time if cond is not None
                else rec.submitted
            )
            rec.succeeded = capi.is_succeeded(obj.status)
            self.transitions.append((name, "terminal", rec.finished))

    def drain(self, now: float = 0.0) -> List[Tuple[str, str, float]]:
        for ev in self._watch.drain():
            self._observe(ev.kind, ev.obj, ev.type == "Deleted", now=now)
        out, self.transitions = self.transitions, []
        return out

    def pending(self) -> int:
        return sum(1 for r in self.jobs.values() if r.finished is None)

    def all_terminal(self) -> bool:
        return self.pending() == 0


# ---------------------------------------------------------------------------
# The harness
# ---------------------------------------------------------------------------


class SoakHarness:
    def __init__(self, cfg: SoakConfig, state_dir: str,
                 progress: Optional[Callable[[Dict[str, Any]], None]] = None):
        self.cfg = cfg
        self.state_dir = state_dir
        self.progress = progress or (lambda info: None)
        self.clock = VirtualClock()
        self.phase = "build"
        self.epochs: List[Dict[str, Any]] = []
        self.disruptions: List[Disruption] = []
        self.submit_retries = 0
        self.failover_report: Optional[Dict[str, Any]] = None
        self.host_chaos = HostChaos()
        # Identities SIGKILLed by the replica tier: the failover rebuild
        # must not resurrect them (a dead process does not come back
        # because the control-plane host moved).
        self._dead_replicas: set = set()
        self._v2_live: List[str] = []  # terminal-TrainJob janitor queue
        self._arrival_cursor = 0
        c = cfg
        self.trace = wl.build_arrival_trace(
            c.seed, c.sim_seconds, c.arrival_per_minute * c.compression,
            c.compression, namespaces=c.namespaces,
        )
        self.orch = ChaosOrchestrator(
            c.seed, c.chaos, c.sim_seconds, compression=c.compression,
            node_recover_s=c.sim(c.recover_seconds),
            failovers=c.failovers,
            # The sixth disruption class: with a sharded replica fleet,
            # kill one operator replica mid-soak (survivors adopt its
            # shards within the grace; INV010 watches the whole time).
            replica_kills=1 if c.operator_replicas > 1 else 0,
        )
        self.orch.pre_disrupt = self._open_for_nodes
        self._op_cfg = self._make_operator_config()
        self._build_primary()

    # -- stack construction ---------------------------------------------

    def _make_operator_config(self) -> OperatorConfig:
        c = self.cfg
        return OperatorConfig(
            gang_scheduler_name="tpu-packer",
            resolve_period=c.sim(c.resolve_seconds),
            min_solve_interval=c.sim(c.min_solve_seconds),
            node_heartbeat_interval=c.sim(c.heartbeat_seconds),
            node_grace_period=c.sim(c.grace_seconds),
            node_toleration_seconds=c.sim(c.toleration_seconds),
            fleet_audit_interval=0.0,  # the harness wires its own plane
            compact_every=c.compact_every_records,
            compact_max_journal_bytes=c.compact_max_journal_bytes,
            replication_wal_ring=c.replication_wal_ring,
            tenancy_enabled=True,
        )

    def _soak_rules(self):
        """The rule catalog with graces matched to this deployment's
        healing cadences: under wire/api chaos the healing machinery for
        cascade GC, expectations, and v2 status sync is the periodic
        resync (plus reconcile backoff, capped at 300s) — the default
        interactive graces would flag states the stack provably heals one
        resync later."""
        resync = self.cfg.sim(self.cfg.resync_seconds)
        audit = self.cfg.sim(self.cfg.audit_seconds)
        slow = resync + 2 * audit + 300.0
        out = []
        for rule in RULES:
            # INV010 rides the slow set too: under the virtual clock a
            # post-kill adoption waits out the lease expiry PLUS a couple
            # of quiescent-step timer gaps, so an "unowned past grace"
            # candidate can legitimately exist for a beat before the
            # survivor's confirm tick lands — persistent candidates are
            # still condemned, exactly like the resync-healed rules.
            if rule.rule_id in ("INV001", "INV004", "INV006", "INV010"):
                out.append(dataclasses.replace(rule, grace=rule.grace + slow))
            else:
                out.append(rule)
        return out

    def _build_stack(self, cluster: Cluster, store: HostStore,
                     standby_lag=None):
        """Cluster services + wire-faulted operator managers + fail-fast
        fleet plane on `cluster` — used for the primary at build time and
        again for the standby at promotion. Builds `operator_replicas`
        (v1 manager, v2 manager) pairs; with > 1 they shard reconcile
        ownership across `operator-shard-{i}` leases and the claims feed
        arms INV010."""
        from training_operator_tpu.__main__ import shard_feed, wire_cluster_services
        from training_operator_tpu.observe import FleetCollector
        from training_operator_tpu.runtime.controller import TrainJobManager

        # The witness order graph is process-global; edges learned against
        # the torn-down primary stack would be stale evidence against the
        # standby's fresh lock instances. Reset per build (the per-pair
        # exception registry survives — exemptions are code, not state).
        locks.reset_witness()
        if locks.lockcheck_enabled():
            from training_operator_tpu.cluster.objects import Event

            def _witness_event(v: Dict[str, Any]) -> None:
                cluster.api.record_event(Event(
                    object_kind="Cluster",
                    object_name="lock-witness",
                    event_type="Warning",
                    reason="LockOrderViolation",
                    message=(
                        f"lock-order cycle {'->'.join(v['cycle'])} closed by "
                        f"{v['pair']} on thread {v['thread']}"
                    ),
                    timestamp=cluster.clock.now(),
                ))

            locks.set_violation_sink(_witness_event)

        c = self.cfg
        replicas = max(1, int(c.operator_replicas))
        wire_cluster_services(cluster, self._op_cfg)
        facade = WireFacade(cluster, self.orch.wire)
        facade.api.enabled = False  # boot over a healthy channel
        pairs: List[Tuple[OperatorManager, TrainJobManager]] = []
        for k in range(replicas):
            if f"soak-op-{k}" in self._dead_replicas:
                continue  # killed earlier; a failover doesn't resurrect it
            mgr = OperatorManager(
                facade, gang_enabled=True,
                reconciles_per_tick=self._op_cfg.controller_threads,
                resync_period=c.sim(c.resync_seconds),
                # Event-driven admission carries the latency; the safety-net
                # poll scales with the solver's own staleness bound, or
                # pending jobs re-reconcile thousands of times over their
                # hours-long quota waits.
                gang_requeue_seconds=c.sim(c.resolve_seconds),
                operator_shards=replicas,
                shard_takeover_grace=c.sim(c.shard_grace_seconds),
                # Stable identities: the post-failover rebuild resumes the
                # replicated shard leases instead of fighting them.
                identity=f"soak-op-{k}",
            )
            register_all(mgr)
            v2 = TrainJobManager(
                facade, resync_period=c.sim(c.resync_seconds),
                namespace_gate=(
                    mgr.owns_namespace if mgr.shard_elector is not None
                    else None
                ),
            )
            pairs.append((mgr, v2))
        facade.api.enabled = True
        api = cluster.api
        self.live_pairs = list(pairs)

        def accumulators() -> Dict[str, Tuple[int, int]]:
            out = {
                "events": (api.event_count(), api.event_cap()),
                "timelines": (api.timelines.count(), api.timelines.max_jobs),
                "workqueue": (
                    sum(len(m.queue) for m, _ in self.live_pairs),
                    c.workqueue_bound,
                ),
            }
            if isinstance(store, StoreShardSet):
                for i, s in enumerate(store.shards):
                    out[f"wal_ring_shard{i}"] = (s.wal_ring_len(), s.wal_ring)
            else:
                out["wal_ring"] = (store.wal_ring_len(), store.wal_ring)
            if self.standby is not None and not self.standby.promoted:
                out["standby_wal_ring"] = (
                    self.standby.store.wal_ring_len(),
                    self.standby.store.wal_ring,
                )
            for i, sb in enumerate(self.shard_standbys):
                if not sb.promoted:
                    out[f"standby_wal_ring_shard{i}"] = (
                        sb.store.wal_ring_len(), sb.store.wal_ring,
                    )
            return out

        def expectations() -> Dict[str, float]:
            out: Dict[str, float] = {}
            for m, _ in self.live_pairs:
                out.update(m.unfulfilled_expectations())
            return out

        sources = FleetSources(
            journal_bytes=store.journal_bytes,
            journal_bound=lambda: (
                store.shards[0].compact_max_bytes
                if isinstance(store, StoreShardSet) else store.compact_max_bytes
            ),
            expectations=expectations,
            accumulators=accumulators,
            replication_lag=standby_lag,
            shards=(
                (lambda: shard_feed([m for m, _ in self.live_pairs]))
                if replicas > 1 else None
            ),
            # INV011: the write plane's ownership contract, audited from
            # the routing sink's own bookkeeping all week.
            store_shards=(
                store.ownership_report
                if isinstance(store, StoreShardSet) else None
            ),
        )
        auditor = InvariantAuditor(
            api, cluster.clock.now, sources=sources,
            interval=c.sim(c.audit_seconds), fail_fast=True,
            toleration_seconds=self._op_cfg.node_toleration_seconds,
            rules=self._soak_rules(),
        )
        collector = FleetCollector(
            cluster, sources=sources, interval=c.sim(c.audit_seconds),
            auditor=auditor,
        )

        def compact_tick():
            store.maybe_compact(api)
            cluster.schedule_after(c.sim(c.compact_check_seconds), compact_tick)

        cluster.schedule_after(c.sim(c.compact_check_seconds), compact_tick)
        return facade, pairs, auditor, collector

    def _build_primary(self) -> None:
        c = self.cfg
        cluster = Cluster(self.clock)
        store = make_store(
            f"{self.state_dir}/primary",
            num_shards=c.store_shards,
            compact_every=c.compact_every_records,
            compact_max_bytes=c.compact_max_journal_bytes,
            wal_ring=c.replication_wal_ring,
        )
        store.load_into(cluster.api)
        store.attach(cluster.api)
        cluster.api.set_event_cap(c.event_cap)
        cluster.add_nodes(make_tpu_pool(
            c.tpu_slices, slice_topology=c.slice_topology))
        cluster.add_nodes(make_cpu_pool(
            c.cpu_nodes, cpu_per_node=c.cpu_per_node))
        # Warm standby(s) tail from seq 0 — nodes included. Sharded plane:
        # one VirtualStandby per write shard, each tailing ITS shard's WAL
        # ring in seq lockstep (a vanilla PR 9 pair, instantiated N times);
        # the whole-store standby exists only in the single-store shape.
        self._shard_failovers = 0
        self.shard_failover_reports: List[Dict[str, Any]] = []
        if c.store_shards > 1:
            self.standby = None
            self.shard_standbys = [
                VirtualStandby(
                    self.clock, store.shards[i],
                    f"{self.state_dir}/standby-shard-{i}", c)
                for i in range(c.store_shards)
            ]
        else:
            self.standby = VirtualStandby(
                self.clock, store, f"{self.state_dir}/standby", c)
            self.shard_standbys = []
        self.cluster = cluster
        self.store = store
        (self.facade, self.pairs, self.auditor,
         self.collector) = self._build_stack(
            cluster, store, standby_lag=(
                self.standby.lag if self.standby is not None
                else self._shard_standby_lag
            ))
        for obj in wl.tenancy_objects(c.team_quota_chips, c.prod_quota_chips):
            cluster.api.create(obj)
        self.orch.attach(cluster, cluster.kubelet,
                         victims=[m._watch for m, _ in self.pairs])
        self.tracker = JobTracker(cluster.api)
        self.node_count = c.tpu_slices * 4 + c.cpu_nodes

    # The submission/reporting pair: always the first LIVE replica (the
    # sixth disruption class may have killed earlier ones).
    @property
    def mgr(self) -> OperatorManager:
        return self.live_pairs[0][0]

    @property
    def v2(self):
        return self.live_pairs[0][1]

    def _kill_replica(self, pick: str) -> None:
        """The sixth orchestrator action: SIGKILL one operator replica
        (HostChaos seam semantics — ticker and watch detached, nothing
        released; its membership + shard leases simply stop renewing and
        survivors adopt the shards at lease expiry). Deterministic victim:
        the action's arg indexes the live list, skipping the last survivor."""
        if len(self.live_pairs) <= 1:
            return
        k = int(pick) % len(self.live_pairs)
        mgr, v2 = self.live_pairs.pop(k)
        self._dead_replicas.add(mgr.identity)
        mgr.kill()
        self.facade.remove_ticker(v2.tick)
        self.cluster.api.unwatch(v2._watch)
        log.info("soak: replica %s KILLED (%d shards stranded: %s)",
                 mgr.identity, len(mgr.owned_shards),
                 sorted(mgr.owned_shards))

    # -- submission ------------------------------------------------------

    def _retry(self, fn, what: str):
        for _ in range(64):
            try:
                return fn()
            except (ApiServerError, ApiUnavailableError):
                self.submit_retries += 1
        raise SoakError(f"{what}: never made it through the wire storm")

    def _submit(self, arrival: wl.Arrival) -> None:
        now = self.clock.now()
        ttl = int(self.cfg.sim(self.cfg.job_ttl_seconds))
        if arrival.kind == "v2":
            runtime, job = wl.build_v2_job(arrival)
            self._retry(lambda: self.v2.submit(runtime), arrival.name)
            self._retry(lambda: self.v2.submit(job), arrival.name)
            self._v2_live.append(arrival.name)
            self.tracker.track(arrival.name, "v2", arrival.queue,
                               arrival.priority, now,
                               namespace=arrival.namespace)
        else:
            job = wl.build_v1_job(arrival, ttl)
            self._retry(lambda: self.mgr.submit(job), arrival.name)
            self.tracker.track(arrival.name, arrival.kind, arrival.queue,
                               arrival.priority, now,
                               namespace=arrival.namespace)
        metrics.soak_arrivals.inc(arrival.kind)

    def _janitor(self) -> None:
        """The user-side GC role for the v2 arm: TrainJobs have no TTL
        field, so terminal ones (and their per-job runtimes) are deleted
        after the soak TTL; the v2 manager's cascade removes the workload.
        Runs against the real api — the janitor is not behind the wire."""
        api = self.cluster.api
        now = self.clock.now()
        ttl = self.cfg.sim(self.cfg.job_ttl_seconds)
        keep = []
        for name in self._v2_live:
            rec = self.tracker.jobs.get(name)
            if rec is None or rec.finished is None:
                keep.append(name)
                continue
            if now - rec.finished < ttl:
                keep.append(name)
                continue
            api.try_delete("TrainJob", rec.namespace, name)
            api.try_delete("TrainingRuntime", rec.namespace, f"{name}-rt")
        self._v2_live = keep

    # -- disruption bookkeeping ------------------------------------------

    def _arrival_namespaces(self) -> List[str]:
        n = self.cfg.namespaces
        if n <= 1:
            return ["default"]
        return [f"soak-ns-{k}" for k in range(n)]

    def _open_for_jobs(self, tier: str, names, t: float) -> None:
        open_jobs = {d.job for d in self.disruptions if d.t_close is None}
        for jname in sorted(set(names)):
            rec = self.tracker.jobs.get(jname)
            if rec is None or rec.finished is not None:
                continue
            if rec.running is None or jname in open_jobs:
                continue  # not yet Running / already disrupted
            self.disruptions.append(Disruption(tier, jname, t))
            open_jobs.add(jname)

    def _open_for_nodes(self, tier: str, nodes) -> None:
        """Open an MTTR record for every RUNNING job with live pods on
        `nodes`. Called before drains (pods still intact) and after kills
        (pods frozen in their last phase)."""
        dead = set(nodes)
        affected = [
            pod.metadata.labels.get(capi.JOB_NAME_LABEL)
            for pod in self.cluster.api.list_refs("Pod")
            if pod.node_name in dead
            and not pod.is_terminal()
            and pod.metadata.labels.get(capi.JOB_NAME_LABEL)
        ]
        self._open_for_jobs(tier, affected, self.clock.now())

    def _open_disruptions(self, log_from: int) -> None:
        """Post-action sampling for kill-shaped disruptions (pods are left
        frozen, so the affected set is still readable); drains are sampled
        pre-action via orchestrator.pre_disrupt."""
        api = self.cluster.api
        for t, tier, action, target in self.orch.log[log_from:]:
            if tier == "node" and action in ("kill", "kill_slice"):
                dead = (
                    [target] if action == "kill"
                    else self.orch._slice_hosts(target)
                )
                self._open_for_nodes(tier, dead)
            elif tier == "pod" and action == "kill":
                # Pod names are soak-unique but the kill log carries no
                # namespace; probe the soak's own (small, fixed) namespace
                # set instead of scanning the whole fleet's pod list per
                # kill — at 10k nodes the scan was the hot path.
                pod = None
                for ns in self._arrival_namespaces():
                    pod = api.try_get("Pod", ns, target)
                    if pod is not None:
                        break
                if pod is not None:
                    jname = pod.metadata.labels.get(capi.JOB_NAME_LABEL)
                    if jname:
                        self._open_for_jobs(tier, [jname], t)

    def _close_disruptions(self, transitions) -> None:
        open_by_job = {
            d.job: d for d in self.disruptions if d.t_close is None
        }
        for name, kind, t in transitions:
            d = open_by_job.get(name)
            if d is None:
                continue
            if kind == "running" and t > d.t_open:
                d.t_close, d.outcome = t, "recovered"
            elif kind == "terminal":
                rec = self.tracker.jobs[name]
                d.t_close = t
                d.outcome = "completed" if rec.succeeded else "failed"
            if d.t_close is not None:
                del open_by_job[name]

    # -- host failover (tier 5) ------------------------------------------

    def _state_digest(self, api) -> Dict[Tuple[str, str, str], int]:
        out = {}
        for kind in api.object_counts():
            for ref in api.list_refs(kind):
                ns = getattr(ref.metadata, "namespace", "") or ""
                out[(kind, ns, ref.metadata.name)] = (
                    ref.metadata.resource_version
                )
        return out

    def _do_failover(self) -> None:
        c = self.cfg
        t_kill = self.clock.now()
        self.phase = "failover"
        pre = self._state_digest(self.cluster.api)
        pre_events = self.cluster.api.event_count()
        # SIGKILL semantics on the primary: store fd abandoned, timers and
        # tickers die with the cluster object (the harness simply never
        # steps it again).
        self.host_chaos.kill_inprocess("soak-primary", store=self.store)
        self.orch.detach()
        # Drain the reachable WAL tail, then verify lockstep parity: the
        # standby must hold EXACTLY the state the primary acknowledged.
        self.standby.pump()
        post = self._state_digest(self.standby.cluster.api)
        parity = (pre == post
                  and self.standby.cluster.api.event_count() == pre_events)
        if not parity:
            missing = len(set(pre) - set(post))
            raise SoakError(
                f"replication parity broken at failover: {missing} objects "
                f"missing, {len(set(post) - set(pre))} unexpected"
            )
        # Promote: the standby cluster becomes the control plane.
        self.standby.promoted = True
        s_cluster = self.standby.cluster
        s_cluster.api.advance_uid_floor()
        version_before = s_cluster.api.version()
        old_kubelet = self.cluster.kubelet
        self.cluster = s_cluster
        self.store = self.standby.store
        (self.facade, self.pairs, self.auditor,
         self.collector) = self._build_stack(s_cluster, self.standby.store)
        # Worker-host death is external state: re-silence dead nodes on
        # the new kubelet before its first heartbeat (orchestrator.attach
        # replays the dead set it tracked on the old kubelet).
        self.orch.kubelet = old_kubelet
        self.orch.attach(s_cluster, s_cluster.kubelet,
                         victims=[m._watch for m, _ in self.pairs])
        self.tracker.rebind(s_cluster.api)
        # Converge until the promoted manager's first acknowledged write.
        mttr_sim = None
        guard = 0
        while mttr_sim is None and guard < 10_000:
            s_cluster.step()
            if s_cluster.api.version() != version_before:
                mttr_sim = self.clock.now() - t_kill
            guard += 1
        self.failover_report = {
            "t_kill_fleet_s": round(c.fleet(t_kill), 1),
            "wal_records_replicated": self.standby.applied,
            "objects_at_failover": len(pre),
            "replication_parity": parity,
            "mttr_first_write_fleet_s": (
                round(c.fleet(mttr_sim), 3) if mttr_sim is not None else None
            ),
            "pending_jobs_at_failover": self.tracker.pending(),
        }
        self.phase = "soak"

    def _shard_standby_lag(self) -> Dict[str, Any]:
        """INV008 feed for the sharded plane: the WORST shard's lag (one
        cold shard standby is exactly as dangerous as a cold whole-store
        standby — failover from it loses that shard's tail)."""
        lags = [sb.lag() for sb in self.shard_standbys if not sb.promoted]
        if not lags:
            return {"role": "primary", "records": 0, "seconds": 0.0,
                    "connected": True, "applied": 0}
        worst = max(lags, key=lambda d: d["records"])
        return {
            "role": "standby",
            "records": worst["records"],
            "seconds": worst["seconds"],
            "connected": True,
            "applied": sum(d["applied"] for d in lags),
        }

    def _do_shard_failover(self) -> None:
        """The host-chaos tier, per-shard: SIGKILL ONE write shard's store
        (journal fd abandoned), drain its standby to the reachable WAL
        tail, verify seq-lockstep parity over exactly that shard's keys,
        and adopt the standby's store into the shard slot. The live
        APIServer and the other shards' journals never notice — that
        independence is the point of the sharded plane, and INV011 keeps
        auditing ownership across the swap."""
        c = self.cfg
        store: StoreShardSet = self.store
        # Deterministic victim rotation, starting on a NON-meta shard so
        # the drill proves a data shard's death leaves cluster-scoped
        # kinds (meta shard) untouched.
        order = [i for i in range(store.num_shards) if i != store.meta_shard]
        order.append(store.meta_shard)
        # A shard whose standby already promoted has no warm follower left
        # to adopt — the drill would compare against a stale WAL tail.
        order = [i for i in order if not self.shard_standbys[i].promoted]
        if not order:
            log.warning("soak: every shard standby already promoted; "
                        "skipping extra shard-failover drill")
            return
        k = order[self._shard_failovers % len(order)]
        self._shard_failovers += 1
        sb = self.shard_standbys[k]
        t_kill = self.clock.now()
        self.phase = "shard-failover"
        pre = {
            key: rv for key, rv in self._state_digest(self.cluster.api).items()
            if store.shard_index(key[0], key[1]) == k
        }
        store.abandon_shard(k)
        sb.pump()
        post = self._state_digest(sb.cluster.api)
        if pre != post:
            raise SoakError(
                f"shard {k} replication parity broken at failover: "
                f"{len(set(pre) - set(post))} objects missing, "
                f"{len(set(post) - set(pre))} unexpected"
            )
        # Adopt: the standby's store (journal already durable with the
        # identical record history) becomes the shard's write target for
        # the routing sink; the standby stops pumping (promoted).
        sb.promoted = True
        store.replace_shard(k, sb.store)
        self.shard_failover_reports.append({
            "shard": k,
            "t_kill_fleet_s": round(c.fleet(t_kill), 1),
            "wal_records_replicated": sb.applied,
            "objects_at_failover": len(pre),
            "replication_parity": True,
            "other_shards_undisturbed": all(
                not store.shards[i].degraded
                for i in range(store.num_shards) if i != k
            ),
        })
        self.phase = "soak"

    # -- main loop -------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        # Injected wire faults make failed reconciles NORMAL here; the
        # manager's per-failure exception logs would emit thousands of
        # intentional tracebacks. Raised to CRITICAL for the run, restored
        # after (the auditor's fail-fast raise is an exception, not a log).
        loggers = [
            logging.getLogger("training_operator_tpu.controllers.manager"),
            logging.getLogger("training_operator_tpu.runtime.controller"),
        ]
        prev_levels = [lg.level for lg in loggers]
        for lg in loggers:
            lg.setLevel(logging.CRITICAL)
        try:
            return self._run()
        finally:
            for lg, level in zip(loggers, prev_levels):
                lg.setLevel(level)

    def _run(self) -> Dict[str, Any]:
        c = self.cfg
        wall_start = _time.monotonic()
        end = c.sim_seconds
        drain_deadline = end + c.sim(c.drain_hours * 3600.0)
        next_epoch = c.sim(c.epoch_seconds)
        epoch_t0_wall = wall_start
        epoch_completed0 = 0
        self.phase = "soak"
        log.info(
            "soak: %d nodes, %d arrivals over %.0f fleet-hours "
            "(compression %.1fx -> %.0f sim-hours), seed %d",
            self.node_count, len(self.trace.arrivals), c.sim_hours,
            c.compression, c.sim_seconds / 3600.0, c.seed,
        )
        while True:
            now = self.clock.now()
            while (self._arrival_cursor < len(self.trace.arrivals)
                   and self.trace.arrivals[self._arrival_cursor].t <= now):
                self._submit(self.trace.arrivals[self._arrival_cursor])
                self._arrival_cursor += 1
            log_from = len(self.orch.log)
            signals = self.orch.run_due(now)
            self._open_disruptions(log_from)
            for sig in signals:
                if sig.startswith("replica_kill:"):
                    self._kill_replica(sig.split(":", 1)[1])
            if "failover" in signals:
                if self.shard_standbys:
                    self._do_shard_failover()
                else:
                    self._do_failover()
            version_before = self.cluster.api.version()
            self.cluster.step()
            if self.standby is not None and not self.standby.promoted:
                self.standby.pump()
            for sb in self.shard_standbys:
                if not sb.promoted:
                    sb.pump()
            transitions = self.tracker.drain(now=self.clock.now())
            self._close_disruptions(transitions)
            now = self.clock.now()
            if now >= next_epoch:
                self._sample_epoch(next_epoch, epoch_completed0,
                                   _time.monotonic() - epoch_t0_wall)
                epoch_completed0 = sum(
                    1 for r in self.tracker.jobs.values()
                    if r.finished is not None)
                epoch_t0_wall = _time.monotonic()
                next_epoch += c.sim(c.epoch_seconds)
                self._janitor()
            if now >= end and self.tracker.all_terminal():
                if self._arrival_cursor >= len(self.trace.arrivals):
                    break
            if now >= drain_deadline:
                raise SoakError(
                    f"drain did not converge: {self.tracker.pending()} jobs "
                    f"still pending {c.drain_hours}h after the last arrival"
                )
            if _time.monotonic() - wall_start > c.max_wall_seconds:
                raise SoakError(
                    f"wall budget exceeded at sim t={now:.0f}s "
                    f"({self._arrival_cursor}/{len(self.trace.arrivals)} "
                    f"arrivals)"
                )
            # Virtual-time advance: only when this step was quiescent.
            if self.cluster.api.version() == version_before:
                candidates = [t for t in (
                    self.cluster.next_timer_at(),
                    self.orch.next_action_at(),
                    (self.trace.arrivals[self._arrival_cursor].t
                     if self._arrival_cursor < len(self.trace.arrivals)
                     else None),
                    next_epoch,
                ) if t is not None]
                nxt = min(candidates) if candidates else now + 1.0
                if nxt > now:
                    self.clock.set(min(nxt, drain_deadline))
        self.phase = "report"
        return self.report(_time.monotonic() - wall_start)

    def _sample_epoch(self, epoch_end_sim: float, completed0: int,
                      wall_s: float) -> None:
        c = self.cfg
        api = self.cluster.api
        counts = api.object_counts()
        completed = sum(
            1 for r in self.tracker.jobs.values() if r.finished is not None)
        sample = {
            "fleet_hour": round(c.fleet(epoch_end_sim) / 3600.0, 2),
            "submitted": self._arrival_cursor,
            "completed": completed,
            "completed_this_epoch": completed - completed0,
            "pending": self.tracker.pending(),
            "pods": counts.get("Pod", 0),
            "store_objects": sum(counts.values()),
            "events": api.event_count(),
            "timelines": api.timelines.count(),
            "journal_bytes": self.store.journal_bytes(),
            "wal_ring": self.store.wal_ring_len(),
            "workqueue": sum(len(m.queue) for m, _ in self.live_pairs),
            "violations": len(self.auditor.last_violations),
            "audits": self.auditor.audits,
            "disruptions": len(self.disruptions),
            "wall_s": round(wall_s, 2),
        }
        self.epochs.append(sample)
        metrics.soak_epochs.inc()
        self.progress({"phase": self.phase, **sample})

    # -- reporting -------------------------------------------------------

    @staticmethod
    def _pct(sorted_vals: List[float], p: float) -> Optional[float]:
        if not sorted_vals:
            return None
        return sorted_vals[min(len(sorted_vals) - 1,
                               int(p * len(sorted_vals)))]

    def _tier_attainment(self) -> Dict[str, Dict[str, Any]]:
        """Per-chaos-tier SLO attainment: join each tier's Disruption rows
        with the tracker's time-to-running records. A job counts against
        every tier that hit it (a pod-killed job that also lost a node shows
        up in both rows); the `undisrupted` row is the control group. The
        per-job target is priority-aware — `high` jobs answer to the tighter
        high_p99 target, everyone else to the normal p99."""
        c = self.cfg
        hit: Dict[str, set] = {}
        for d in self.disruptions:
            hit.setdefault(d.tier, set()).add(d.job)
        disrupted_any = set().union(*hit.values()) if hit else set()
        ran = {
            name: r for name, r in self.tracker.jobs.items()
            if r.running is not None
        }
        out: Dict[str, Dict[str, Any]] = {}
        rows = sorted(hit.items()) + [
            ("undisrupted", set(ran) - disrupted_any)]
        for tier, names in rows:
            pairs = [
                (c.fleet(ran[n].running - ran[n].submitted),
                 c.slo_high_p99_ttr_s if ran[n].priority == "high"
                 else c.slo_p99_ttr_s)
                for n in sorted(names) if n in ran
            ]
            ttrs = sorted(ttr for ttr, _ in pairs)
            within = sum(1 for ttr, tgt in pairs if ttr <= tgt)
            out[tier] = {
                "jobs": len(names),
                "ran": len(ttrs),
                "p50_ttr_s": self._pct(ttrs, 0.50),
                "p99_ttr_s": self._pct(ttrs, 0.99),
                "attainment": (
                    round(within / len(ttrs), 4) if ttrs else None),
            }
        return out

    def report(self, wall_s: float) -> Dict[str, Any]:
        c = self.cfg
        jobs = self.tracker.jobs
        done = [r for r in jobs.values() if r.finished is not None]
        ttr_all = sorted(
            c.fleet(r.running - r.submitted)
            for r in jobs.values() if r.running is not None
        )
        ttr_high = sorted(
            c.fleet(r.running - r.submitted)
            for r in jobs.values()
            if r.running is not None and r.priority == "high"
        )
        sim_minutes = c.fleet(self.clock.now()) / 60.0
        mttr = sorted(
            c.fleet(d.t_close - d.t_open)
            for d in self.disruptions
            if d.t_close is not None and d.outcome == "recovered"
        )
        growth = self._growth_audit()
        slo = {
            "p50_ttr_s": self._pct(ttr_all, 0.50),
            "p99_ttr_s": self._pct(ttr_all, 0.99),
            "high_p99_ttr_s": self._pct(ttr_high, 0.99),
            "targets": {
                "p50_ttr_s": c.slo_p50_ttr_s,
                "p99_ttr_s": c.slo_p99_ttr_s,
                "high_p99_ttr_s": c.slo_high_p99_ttr_s,
            },
        }
        slo["held"] = bool(
            ttr_all
            and slo["p50_ttr_s"] <= c.slo_p50_ttr_s
            and slo["p99_ttr_s"] <= c.slo_p99_ttr_s
            and (not ttr_high or slo["high_p99_ttr_s"] <= c.slo_high_p99_ttr_s)
        )
        slo["by_tier"] = self._tier_attainment()
        return {
            "nodes": self.node_count,
            "fleet_hours": c.sim_hours,
            "compression": c.compression,
            "seed": c.seed,
            "wall_seconds": round(wall_s, 1),
            "jobs": {
                "submitted": len(jobs),
                "completed": len(done),
                "succeeded": sum(1 for r in done if r.succeeded),
                "failed": sum(1 for r in done if not r.succeeded),
                "gc_unobserved": self.tracker.gc_unobserved,
                "by_kind": self._by_kind(),
            },
            "throughput": {
                "jobs_per_fleet_minute": (
                    round(len(done) / sim_minutes, 3) if sim_minutes else None
                ),
                "min_epoch_jobs": min(
                    (e["completed_this_epoch"] for e in self.epochs),
                    default=None),
                "epochs": len(self.epochs),
            },
            "slo": slo,
            "mttr": {
                "samples": len(mttr),
                "p50_s": self._pct(mttr, 0.50),
                "p99_s": self._pct(mttr, 0.99),
                "disruptions": {
                    outcome: sum(1 for d in self.disruptions
                                 if d.outcome == outcome)
                    for outcome in
                    ("recovered", "completed", "failed", "")
                },
            },
            "chaos": self.orch.counts(),
            "wire": {
                "injected": dict(self.orch.wire.injected),
                "tick_aborts": self.facade.tick_aborts,
                "submit_retries": self.submit_retries,
            },
            "api_chaos_conflicts": (
                self.orch.api_chaos.injected_conflicts
                if self.orch.api_chaos else 0
            ),
            "failover": self.failover_report,
            "auditor": {
                "audits": self.auditor.audits,
                "violations": len(self.auditor.last_violations),
                "fail_fast": True,
            },
            "growth": growth,
            "replication": (
                {
                    "records_applied": self.standby.applied,
                    "final_lag_records": self.standby.lag_records,
                }
                if self.standby is not None else
                # Sharded plane: one lockstep standby per write shard.
                {
                    "records_applied": sum(
                        sb.applied for sb in self.shard_standbys),
                    "final_lag_records": max(
                        (sb.lag_records for sb in self.shard_standbys
                         if not sb.promoted), default=0),
                }
            ),
            **({"store_shards": {
                "num_shards": c.store_shards,
                "meta_shard": self.store.meta_shard,
                "failovers": list(self.shard_failover_reports),
                "ownership": self.store.ownership_report(),
            }} if isinstance(self.store, StoreShardSet) else {}),
            **({"shards": {
                "replicas": c.operator_replicas,
                "survivors": len(self.live_pairs),
                "handoffs": sum(
                    m.shard_elector.handoffs for m, _ in self.live_pairs
                ),
                "rebalances": sum(
                    m.shard_elector.rebalances for m, _ in self.live_pairs
                ),
                "owned": {
                    m.identity: sorted(m.owned_shards)
                    for m, _ in self.live_pairs
                },
            }} if c.operator_replicas > 1 else {}),
        }

    def _by_kind(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for r in self.tracker.jobs.values():
            bucket = out.setdefault(
                r.kind, {"submitted": 0, "succeeded": 0, "failed": 0})
            bucket["submitted"] += 1
            if r.finished is not None:
                bucket["succeeded" if r.succeeded else "failed"] += 1
        return out

    def _growth_audit(self) -> Dict[str, Any]:
        """The bounded-growth verdict: every audited accumulator's peak
        over the whole soak vs its configured bound (INV009 would have
        fail-fasted the run on a live breach; this is the artifact's
        evidence that the bounds HELD, with headroom numbers)."""
        c = self.cfg
        bounds = {
            "events": c.event_cap,
            "timelines": self.cluster.api.timelines.max_jobs,
            "journal_bytes": c.compact_max_journal_bytes,
            "wal_ring": c.replication_wal_ring,
            "workqueue": c.workqueue_bound,
        }
        out = {}
        for key, bound in bounds.items():
            peak = max((e.get(key, 0) for e in self.epochs), default=0)
            out[key] = {
                "peak": peak, "bound": bound,
                "within": peak <= bound,
            }
        out["store_objects_first_last"] = (
            (self.epochs[0]["store_objects"], self.epochs[-1]["store_objects"])
            if self.epochs else None
        )
        return out
