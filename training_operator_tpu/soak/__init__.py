"""Time-compressed fleet soak harness (ROADMAP item 3).

`SoakHarness` drives the full control plane through simulated days of
fleet life on the virtual clock — sustained heavy-tailed arrivals, all
five chaos tiers live at once, rolling maintenance, a mid-soak host
failover — under the fail-fast invariant auditor. See soak/harness.py for
the architecture and soak/orchestrator.py for the single-seed chaos
schedule derivation.
"""

from training_operator_tpu.soak.harness import (
    SoakConfig,
    SoakError,
    SoakHarness,
    VirtualStandby,
    WireFacade,
)
from training_operator_tpu.soak.orchestrator import ChaosOrchestrator, derive_seed
from training_operator_tpu.soak.workload import (
    Arrival,
    SoakTrace,
    build_arrival_trace,
    build_v1_job,
    build_v2_job,
    tenancy_objects,
)

__all__ = [
    "Arrival",
    "ChaosOrchestrator",
    "SoakConfig",
    "SoakError",
    "SoakHarness",
    "SoakTrace",
    "VirtualStandby",
    "WireFacade",
    "build_arrival_trace",
    "build_v1_job",
    "build_v2_job",
    "derive_seed",
    "tenancy_objects",
]
