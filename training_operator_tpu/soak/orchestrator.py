"""Single-seed chaos orchestration: one `soak_seed` drives all five tiers.

Each chaos tier owns its injection *machinery* (cluster/chaos.py); what a
soak needs on top is one authority over *when* every tier fires, on one
virtual clock, derived from one seed — so a failing week of fleet life is
replayable as a whole, not per-tier. The orchestrator precomputes a merged
action schedule at construction (pure function of the seed + config) and
executes due actions from the harness loop:

  pod    ChaosMonkey.strike_once (seeded victim pick) on a Poisson schedule
  node   NodeChaos.strike_once with reboot-class recovery, occasional
         whole-slice kills, and rolling maintenance windows (cordon+drain,
         uncordon at window end) walking the slice inventory
  api    APIChaos continuous conflict/drop/dup rates against the operator's
         watch queues (bound at attach, rebound after failover)
  wire   WireChaos continuous error/reset decisions, sampled by the
         harness's in-process wire boundary (soak/harness.py WireFacade)
  host   control-plane host kill + standby promotion, executed by the
         harness (the orchestrator only schedules it)

Recovery and window-end timers are orchestrator actions, NOT cluster
timers: a host failover kills the dead cluster's timer heap, but a worker
node mid-reboot comes back regardless of who runs the control plane — so
the orchestrator re-arms its own pending actions against the promoted
cluster instead of losing them with the old one.

`log` records every executed action as (sim_time, tier, action, target);
together with the arrival trace it is the replay pin: two runs from the
same seed produce identical logs.
"""

from __future__ import annotations

import heapq
import itertools
import random
import zlib
from typing import Dict, List, Optional, Tuple

from training_operator_tpu.cluster.chaos import (
    APIChaos,
    ChaosMonkey,
    NodeChaos,
    WireChaos,
)
from training_operator_tpu.utils import metrics

# Base cadences at intensity 1.0, in simulated seconds (scaled down by the
# harness's compression factor before they reach the orchestrator).
POD_KILL_MEAN_S = 2 * 3600.0        # one pod kill every ~2 sim hours
NODE_KILL_MEAN_S = 6 * 3600.0       # one host death every ~6 sim hours
NODE_RECOVER_S = 1800.0             # reboot-class outage length
SLICE_KILL_MEAN_S = 48 * 3600.0     # correlated whole-slice failure
MAINTENANCE_PERIOD_S = 8 * 3600.0   # one slice enters maintenance
MAINTENANCE_WINDOW_S = 3600.0       # ... for this long
# Continuous-rate tiers at intensity 1.0 (capped after scaling).
API_CONFLICT_RATE = 0.03
API_DROP_RATE = 0.015
API_DUP_RATE = 0.008
WIRE_ERROR_RATE = 0.015
WIRE_RESET_RATE = 0.008


def derive_seed(soak_seed: int, tag: str) -> int:
    """Stable per-consumer sub-seed: crc32 keeps it deterministic across
    processes and Python versions (hash() is salted)."""
    return zlib.crc32(f"{soak_seed}:{tag}".encode()) & 0x7FFFFFFF


class ChaosOrchestrator:
    def __init__(
        self,
        seed: int,
        intensity: Dict[str, float],
        sim_seconds: float,
        compression: float = 1.0,
        node_recover_s: Optional[float] = None,
        failovers: Optional[int] = None,
        replica_kills: int = 0,
    ):
        self.seed = seed
        self.intensity = dict(intensity)
        self.sim_seconds = sim_seconds
        self.compression = max(1e-9, compression)
        self.node_recover_s = (
            node_recover_s if node_recover_s is not None
            else NODE_RECOVER_S / self.compression
        )
        self.log: List[Tuple[float, str, str, str]] = []
        # Optional callback(tier, node_names) fired BEFORE a disruption
        # that synchronously changes pod state (maintenance drains): the
        # harness snapshots which running jobs are affected while their
        # pods still exist; kills leave pods frozen, so those are sampled
        # after the fact.
        self.pre_disrupt = None
        # (time, seq, tier, action, arg) min-heap; seq breaks time ties
        # deterministically.
        self._actions: List[Tuple[float, int, str, str, Optional[str]]] = []
        self._seq = itertools.count()
        self._rebinds = 0
        # Bound tier objects (attach()).
        self.cluster = None
        self.kubelet = None
        self.monkey: Optional[ChaosMonkey] = None
        self.nodes: Optional[NodeChaos] = None
        self.api_chaos: Optional[APIChaos] = None
        self.wire: Optional[WireChaos] = None
        self._build_schedule(failovers, replica_kills)

    # -- schedule construction (pure function of seed + config) ---------

    def _poisson_times(self, rng: random.Random, mean_gap: float) -> List[float]:
        out, t = [], 0.0
        while True:
            t += rng.expovariate(1.0 / mean_gap)
            if t >= self.sim_seconds:
                return out
            out.append(t)

    def _push(self, t: float, tier: str, action: str, arg: Optional[str] = None):
        heapq.heappush(self._actions, (t, next(self._seq), tier, action, arg))

    def _build_schedule(self, failovers: Optional[int],
                        replica_kills: int = 0) -> None:
        scale = self.compression
        if self.intensity.get("pod", 0.0) > 0:
            rng = random.Random(derive_seed(self.seed, "sched-pod"))
            mean = POD_KILL_MEAN_S / self.intensity["pod"] / scale
            for t in self._poisson_times(rng, mean):
                self._push(t, "pod", "kill")
        if self.intensity.get("node", 0.0) > 0:
            i = self.intensity["node"]
            rng = random.Random(derive_seed(self.seed, "sched-node"))
            for t in self._poisson_times(rng, NODE_KILL_MEAN_S / i / scale):
                self._push(t, "node", "kill")
            rng = random.Random(derive_seed(self.seed, "sched-slice"))
            for t in self._poisson_times(rng, SLICE_KILL_MEAN_S / i / scale):
                self._push(t, "node", "kill_slice")
            # Rolling maintenance: deterministic cadence (planned work is
            # calendar-shaped, not Poisson), slice picked by counter.
            period = MAINTENANCE_PERIOD_S / i / scale
            window = MAINTENANCE_WINDOW_S / scale
            t, k = period, 0
            while t < self.sim_seconds:
                self._push(t, "node", "maintenance_begin", str(k))
                self._push(t + window, "node", "maintenance_end", str(k))
                t += period
                k += 1
        if failovers is None:
            failovers = 1 if self.intensity.get("host", 0.0) > 0 else 0
        # The host tier is BINARY (documented in config.soak_chaos): the
        # harness runs exactly one warm standby, so there is exactly one
        # failover to schedule — a second would kill the promoted host
        # with nothing left to promote.
        failovers = min(int(failovers), 1)
        if failovers:
            rng = random.Random(derive_seed(self.seed, "sched-host"))
            for k in range(failovers):
                # Mid-soak, jittered: never at the very start or end.
                frac = (k + 1) / (failovers + 1)
                t = self.sim_seconds * (frac + rng.uniform(-0.08, 0.08))
                self._push(min(max(t, 1.0), self.sim_seconds * 0.9),
                           "host", "failover")
        # The sixth disruption class (sharded operator fleets): kill an
        # operator REPLICA mid-soak — the HostChaos seam one layer up from
        # the control-plane host. Scheduled like the failover (mid-soak,
        # jittered), executed by the harness (it owns the manager objects);
        # the arg deterministically indexes the live replica list.
        if replica_kills > 0:
            rng = random.Random(derive_seed(self.seed, "sched-replica"))
            for k in range(int(replica_kills)):
                frac = (k + 1) / (replica_kills + 1)
                t = self.sim_seconds * (frac + rng.uniform(-0.08, 0.08))
                self._push(min(max(t, 1.0), self.sim_seconds * 0.9),
                           "replica", "kill", str(rng.randrange(16)))
        self.wire = WireChaos(
            seed=derive_seed(self.seed, "wire"),
            error_rate=min(0.25, WIRE_ERROR_RATE * self.intensity.get("wire", 0.0)),
            reset_rate=min(0.25, WIRE_RESET_RATE * self.intensity.get("wire", 0.0)),
        )

    # -- binding to a (possibly promoted) cluster ------------------------

    def attach(self, cluster, kubelet, victims) -> None:
        """Bind the tier machinery to a live cluster. Called once at soak
        start and again after each host failover (`victims` = the new
        operator's watch queues; per-incarnation sub-seeds keep victim
        picks deterministic across the rebind)."""
        inc = self._rebinds
        self._rebinds += 1
        dead = kubelet.dead_nodes() if self.kubelet is None else (
            self.kubelet.dead_nodes()
        )
        self.cluster = cluster
        self.monkey = ChaosMonkey(
            cluster, kubelet,
            seed=derive_seed(self.seed, f"pod/{inc}"), budget=0,
        )
        self.nodes = NodeChaos(
            cluster, kubelet,
            seed=derive_seed(self.seed, f"node/{inc}"), budget=0,
        )
        if self.api_chaos is not None:
            self.api_chaos.stop()
        i = self.intensity.get("api", 0.0)
        self.api_chaos = APIChaos(
            cluster, seed=derive_seed(self.seed, f"api/{inc}"),
            conflict_rate=min(0.25, API_CONFLICT_RATE * i),
            drop_rate=min(0.25, API_DROP_RATE * i),
            dup_rate=min(0.25, API_DUP_RATE * i),
            victims=list(victims),
        ) if i > 0 else None
        # Worker-node death is external state: re-silence it on the new
        # kubelet BEFORE its first heartbeat resurrects the leases.
        if inc > 0:
            for name in sorted(dead):
                kubelet.kill_node(name)
        self.kubelet = kubelet

    def detach(self) -> None:
        if self.api_chaos is not None:
            self.api_chaos.stop()
            self.api_chaos = None
        if self.monkey is not None:
            self.monkey.stop()
        if self.nodes is not None:
            self.nodes.stop()

    # -- execution -------------------------------------------------------

    def next_action_at(self) -> Optional[float]:
        return self._actions[0][0] if self._actions else None

    def _slice_ids(self) -> List[str]:
        return sorted({
            n.accelerator.tpu_slice
            for n in self.cluster.api.list_refs("Node")
            if n.accelerator.kind == "tpu" and n.accelerator.tpu_slice
        })

    def _record(self, tier: str, action: str, target: str) -> None:
        self.log.append((self.cluster.clock.now(), tier, action, target))
        metrics.soak_disruptions.inc(tier)

    def run_due(self, now: float) -> List[str]:
        """Execute every action due at `now`; returns the special signals
        the HARNESS must act on ("failover") — the orchestrator cannot kill
        the control plane it is riding on."""
        signals: List[str] = []
        while self._actions and self._actions[0][0] <= now:
            _, _, tier, action, arg = heapq.heappop(self._actions)
            if tier == "pod" and action == "kill":
                victim = self.monkey.strike_once()
                if victim:
                    self._record("pod", "kill", victim)
            elif tier == "node" and action == "kill":
                victim = self.nodes.strike_once()
                if victim:
                    self._record("node", "kill", victim)
                    self._push(now + self.node_recover_s,
                               "node", "recover", victim)
            elif tier == "node" and action == "recover":
                self.nodes.recover_node(arg)
                self._record("node", "recover", arg)
            elif tier == "node" and action == "kill_slice":
                slices = self._slice_ids()
                if slices:
                    sid = slices[
                        random.Random(
                            derive_seed(self.seed, f"slicepick/{now:.3f}")
                        ).randrange(len(slices))
                    ]
                    members = self.nodes.kill_slice(sid)
                    self._record("node", "kill_slice", sid)
                    for m in members:
                        self._push(now + self.node_recover_s,
                                   "node", "recover", m)
            elif tier == "node" and action == "maintenance_begin":
                from training_operator_tpu.controllers.nodelifecycle import (
                    drain_node,
                )

                slices = self._slice_ids()
                if slices:
                    sid = slices[int(arg) % len(slices)]
                    hosts = self._slice_hosts(sid)
                    if self.pre_disrupt is not None:
                        self.pre_disrupt("node", hosts)
                    for h in hosts:
                        drain_node(self.cluster.api, h, now=now)
                    self._record("node", "maintenance_begin", sid)
            elif tier == "node" and action == "maintenance_end":
                from training_operator_tpu.controllers.nodelifecycle import (
                    uncordon_node,
                )

                slices = self._slice_ids()
                if slices:
                    sid = slices[int(arg) % len(slices)]
                    for h in self._slice_hosts(sid):
                        uncordon_node(self.cluster.api, h, now=now)
                    self._record("node", "maintenance_end", sid)
            elif tier == "host" and action == "failover":
                self._record("host", "failover", "primary")
                signals.append("failover")
            elif tier == "replica" and action == "kill":
                self._record("replica", "kill", arg)
                signals.append(f"replica_kill:{arg}")
        return signals

    def _slice_hosts(self, slice_id: str) -> List[str]:
        return sorted(
            n.metadata.name
            for n in self.cluster.api.list_refs("Node")
            if n.accelerator.kind == "tpu"
            and n.accelerator.tpu_slice == slice_id
        )

    # -- replay pin ------------------------------------------------------

    def replay_log(self) -> List[Tuple[float, str, str, str]]:
        return [(round(t, 6), tier, action, target)
                for t, tier, action, target in self.log]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for _, tier, action, _t in self.log:
            out[f"{tier}:{action}"] = out.get(f"{tier}:{action}", 0) + 1
        return out
