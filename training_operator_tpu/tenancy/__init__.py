"""Multi-tenant fleet scheduling: queues, quotas, priority, preemption.

The subsystem the reference delegates to sigs.k8s.io/kueue + volcano.sh
(SURVEY.md §deps, §2.3): a contested TPU fleet needs per-team quota
(ClusterQueue), job importance (PriorityClass), a fair-share arbiter in
front of the gang solver, and checkpoint-aware preemption so a displaced
TrainJob resumes from its saved step instead of step 0.

Layout:
  api.py      PriorityClass / ClusterQueue kinds + validation + admission
  arbiter.py  quota accounting, DRF-style ordering, preemption planning,
              and the pod-preemption primitive the gang scheduler executes
"""

from training_operator_tpu.tenancy.api import (
    PREEMPTION_NEVER,
    PREEMPTION_PREEMPT_LOWER,
    PRIORITY_CLASS_LABEL,
    QUEUE_LABEL,
    ClusterQueue,
    PriorityClass,
    register_tenancy_admission,
    validate_cluster_queue,
    validate_priority_class,
)
from training_operator_tpu.tenancy.arbiter import (
    Arbitration,
    PreemptionDecision,
    TenancyArbiter,
    admitted_usage,
    pending_usage,
    preempt_pod,
    queue_for_group,
    resolve_priority,
)

__all__ = [
    "Arbitration",
    "ClusterQueue",
    "PREEMPTION_NEVER",
    "PREEMPTION_PREEMPT_LOWER",
    "PRIORITY_CLASS_LABEL",
    "PreemptionDecision",
    "PriorityClass",
    "QUEUE_LABEL",
    "TenancyArbiter",
    "admitted_usage",
    "pending_usage",
    "preempt_pod",
    "queue_for_group",
    "register_tenancy_admission",
    "resolve_priority",
    "validate_cluster_queue",
    "validate_priority_class",
]
