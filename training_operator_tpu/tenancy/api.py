"""Tenancy API kinds: PriorityClass and ClusterQueue.

Modeled on scheduling.k8s.io/v1 PriorityClass and kueue's ClusterQueue
(the two dependencies the reference links for exactly this job — SURVEY.md
§deps), reduced to the fields the fair-share arbiter consumes:

  PriorityClass   a named integer importance + whether gangs of this class
                  may displace lower-priority work (`preemption_policy`).
  ClusterQueue    a team's share of the chip pool: per-resource nominal
                  `quota`, a `borrowing_limit` it may exceed quota by when
                  the pool has idle capacity, a fair-share `weight`, and
                  the namespaces whose jobs default into it.

Both are cluster-scoped (namespace ""), stored/watched/journaled like any
other kind (cluster/wire.py KIND_REGISTRY), and guarded by admission
hooks registered via `register_tenancy_admission`.

Jobs reach the tenancy plane through the surfaces that already exist:
v1 jobs via RunPolicy.scheduling_policy.{queue,priority_class} (on the
PodGroup wire since the seed — used by nothing until this subsystem), and
v2 TrainJobs via the QUEUE_LABEL / PRIORITY_CLASS_LABEL labels that the
workload builder copies onto the generated job's scheduling policy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from training_operator_tpu.api.jobs import ObjectMeta

# TrainJob (and any job) labels routing into the tenancy plane — the kueue
# `kueue.x-k8s.io/queue-name` label analogue, under our API group.
QUEUE_LABEL = "tenancy.tpu.dev/queue"
PRIORITY_CLASS_LABEL = "tenancy.tpu.dev/priority-class"

# PriorityClass.preemption_policy values (scheduling.k8s.io parity).
PREEMPTION_PREEMPT_LOWER = "PreemptLowerPriority"
PREEMPTION_NEVER = "Never"


@dataclass
class PriorityClass:
    """Named job importance (scheduling.k8s.io/v1 PriorityClass shape)."""

    KIND = "PriorityClass"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    # PreemptLowerPriority: gangs of this class may displace strictly
    # lower-priority admitted gangs when infeasible. Never: they wait.
    preemption_policy: str = PREEMPTION_PREEMPT_LOWER
    # Applies to gangs that name no class at all (at most one class should
    # set it; admission enforces nothing — ties resolve by highest value
    # then name, deterministically).
    global_default: bool = False
    description: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return ""


@dataclass
class ClusterQueue:
    """One team's share of the pool (kueue ClusterQueue, reduced).

    `quota` is the nominal per-resource share (e.g. {"tpu.dev/chips": 64});
    `borrowing_limit` is how far past quota the queue may stretch into idle
    capacity, per resource (absent key = no borrowing for that resource).
    `weight` scales the queue's dominant share in fair-share ordering
    (weight 2 = entitled to twice the share before it yields). `namespaces`
    routes jobs that name no queue: a job from a listed namespace defaults
    into this queue.
    """

    KIND = "ClusterQueue"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    quota: Dict[str, float] = field(default_factory=dict)
    borrowing_limit: Dict[str, float] = field(default_factory=dict)
    weight: float = 1.0
    namespaces: List[str] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return ""

    def cap(self, resource: str) -> float:
        """quota + borrowing for one resource — THE over-admission bound
        (the arbiter admits against it; INV007 audits against it)."""
        return self.quota.get(resource, 0.0) + self.borrowing_limit.get(
            resource, 0.0
        )


def validate_priority_class(pc: PriorityClass) -> None:
    from training_operator_tpu.api.validation import ValidationError, is_dns1035_label

    errs: List[str] = []
    if not pc.metadata.name:
        errs.append("metadata.name: required")
    elif not is_dns1035_label(pc.metadata.name):
        errs.append(f"metadata.name: {pc.metadata.name!r} is not a DNS-1035 label")
    if not isinstance(pc.value, int) or isinstance(pc.value, bool):
        errs.append(f"value: {pc.value!r} must be an integer")
    elif not -2_000_000_000 <= pc.value <= 2_000_000_000:
        # k8s caps user classes at 1e9; we only need "fits in the wire's
        # JSON int and sorts sanely".
        errs.append(f"value: {pc.value} out of range")
    if pc.preemption_policy not in (PREEMPTION_PREEMPT_LOWER, PREEMPTION_NEVER):
        errs.append(
            f"preemptionPolicy: {pc.preemption_policy!r} must be "
            f"{PREEMPTION_PREEMPT_LOWER!r} or {PREEMPTION_NEVER!r}"
        )
    if errs:
        raise ValidationError(errs)


def validate_cluster_queue(cq: ClusterQueue) -> None:
    from training_operator_tpu.api.validation import ValidationError, is_dns1035_label

    errs: List[str] = []
    if not cq.metadata.name:
        errs.append("metadata.name: required")
    elif not is_dns1035_label(cq.metadata.name):
        errs.append(f"metadata.name: {cq.metadata.name!r} is not a DNS-1035 label")
    for res, val in cq.quota.items():
        if val < 0:
            errs.append(f"quota[{res}]: {val} must be >= 0")
    for res, val in cq.borrowing_limit.items():
        if val < 0:
            errs.append(f"borrowingLimit[{res}]: {val} must be >= 0")
    if cq.weight <= 0:
        # weight divides the dominant share; zero would make the queue
        # infinitely hungry (share 0 forever) and divide-by-zero besides.
        errs.append(f"weight: {cq.weight} must be > 0")
    if errs:
        raise ValidationError(errs)


def _admit_priority_class(pc: PriorityClass) -> None:
    # Cluster-scoped kinds live at namespace "" (the ClusterTrainingRuntime
    # convention); defaulting here keeps every lookup path agreeing on the
    # key even when the client left ObjectMeta's "default" in place.
    pc.metadata.namespace = ""
    validate_priority_class(pc)


def _admit_cluster_queue(cq: ClusterQueue) -> None:
    cq.metadata.namespace = ""
    validate_cluster_queue(cq)


def register_tenancy_admission(api) -> None:
    """Admission for the tenancy kinds, on whichever APIServer stores them
    (host role and standalone both route through here so a malformed quota
    object can never enter the store and wedge the arbiter)."""
    api.register_admission(PriorityClass.KIND, _admit_priority_class)
    api.register_admission(ClusterQueue.KIND, _admit_cluster_queue)
