"""Fair-share arbiter: the admission layer in front of the gang solver.

Sits between pending PodGroups and `GangScheduler`'s batch solve (the role
kueue plays in front of the reference's gang scheduler). Three duties:

1. **Quota admission.** A gang enters the solve only while its queue's
   admitted usage + the gang's demand stays within quota + borrowing for
   every quota'd resource (ClusterQueue.cap). Blocked gangs stay Pending
   with a QuotaExceeded event; the gang scheduler re-arbitrates when
   capacity frees or a tenancy object changes.

2. **Ordering.** Admissible gangs are handed to the placer in priority
   tiers (descending PriorityClass value; one `place()` call per tier, so
   the solver can never trade a high-priority gang away for better packing
   of a lower one). Within a tier, queues take turns by ascending weighted
   dominant share (DRF-style: a queue's share is its most-constrained
   quota fraction, divided by its weight), with preempted gangs at the
   front of their queue's line (fair-share debt: displaced work re-enters
   first). Gangs pending past `starvation_seconds` bypass the priority
   tiers entirely (FIFO front) — the starvation guard — but never the
   quota gate.

3. **Preemption planning.** A gang that stayed unplaced after its tier's
   solve may displace admitted work: victims are chosen cheapest-first
   (lowest priority, then least displaced demand, then youngest — the
   least checkpoint progress lost) among strictly-lower-priority gangs —
   or, when the preemptor's queue is reclaiming its nominal quota,
   borrowing gangs of any queue at <= its priority. Only plans that
   provably cover the capacity deficit are returned (no futile
   evictions), and a gang already preempted `max_preemptions` times is
   immune (preemption's own starvation guard). Execution — checkpoint
   marking, eviction, requeue — is the gang scheduler's job
   (`GangScheduler._preempt_group`), so the arbiter stays a pure planner.

The checkpoint contract: the victim's progress at eviction is recorded on
its PodGroup (`checkpointed_seconds`), standing in for the trainer's own
save-on-SIGTERM (trainer/checkpoint.py already auto-resumes from the
latest step on restart). The engine subtracts it from the simulated run
time when the gang's pods are recreated — resumed from step, not step 0 —
and the eviction rides the PR 5 retryable path, so the restart budget is
untouched.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from training_operator_tpu.cluster.objects import PodGroup, PodGroupPhase
from training_operator_tpu.engine.core import PREEMPTED_MESSAGE_PREFIX
from training_operator_tpu.tenancy.api import (
    PREEMPTION_NEVER,
    ClusterQueue,
    PriorityClass,
)

_EPS = 1e-9

ADMITTED_PHASES = (PodGroupPhase.INQUEUE, PodGroupPhase.RUNNING)
PENDING_PHASES = (PodGroupPhase.PENDING, PodGroupPhase.UNSCHEDULABLE)


def resolve_priority(
    pg: PodGroup, classes: Dict[str, PriorityClass]
) -> Tuple[int, str]:
    """(value, preemption_policy) for one gang. An unnamed class falls to
    the global default (highest value wins ties, then name — deterministic);
    a name with no object resolves to (0, Never) — value 0, no preemption
    rights — and speclint TEN001 rejects that reference at admission for
    v2 jobs."""
    name = pg.priority_class
    if name:
        pc = classes.get(name)
        if pc is not None:
            return pc.value, pc.preemption_policy
        return 0, PREEMPTION_NEVER
    defaults = [c for c in classes.values() if c.global_default]
    if defaults:
        pc = max(defaults, key=lambda c: (c.value, c.metadata.name))
        return pc.value, pc.preemption_policy
    return 0, PREEMPTION_NEVER


def queue_for_group(
    pg: PodGroup, queues: Dict[str, ClusterQueue]
) -> Optional[ClusterQueue]:
    """The ClusterQueue a gang charges: its named queue, else the queue
    whose `namespaces` lists the gang's namespace (first by name), else
    none (unconstrained — a cluster without tenancy objects behaves
    exactly like the pre-tenancy scheduler)."""
    if pg.queue:
        return queues.get(pg.queue)
    ns = pg.namespace
    for name in sorted(queues):
        if ns in queues[name].namespaces:
            return queues[name]
    return None


def _usage(
    groups: Iterable[PodGroup],
    queues: Dict[str, ClusterQueue],
    phases: Tuple[PodGroupPhase, ...],
) -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for pg in groups:
        if pg.phase not in phases:
            continue
        q = queue_for_group(pg, queues)
        if q is None:
            continue
        bucket = out.setdefault(q.name, {})
        for res, val in (pg.min_resources or {}).items():
            bucket[res] = bucket.get(res, 0.0) + val
    return out


def admitted_usage(
    groups: Iterable[PodGroup], queues: Dict[str, ClusterQueue]
) -> Dict[str, Dict[str, float]]:
    """Per-queue resources held by admitted (Inqueue/Running) gangs — THE
    accounting the arbiter admits against, INV007 audits against, and the
    fleet queue gauges publish; one function so they cannot disagree."""
    return _usage(groups, queues, ADMITTED_PHASES)


def pending_usage(
    groups: Iterable[PodGroup], queues: Dict[str, ClusterQueue]
) -> Dict[str, Dict[str, float]]:
    """Per-queue resources demanded by queued (Pending/Unschedulable)
    gangs — the fleet plane's queue-depth view."""
    return _usage(groups, queues, PENDING_PHASES)


def dominant_share(
    queue: ClusterQueue, usage: Dict[str, float]
) -> float:
    """Weighted dominant share: the queue's most-constrained quota
    fraction, divided by its weight (DRF over the quota'd resources)."""
    share = 0.0
    for res, quota in queue.quota.items():
        if quota > 0:
            share = max(share, usage.get(res, 0.0) / quota)
    return share / queue.weight


@dataclass
class Arbitration:
    """One cycle's admission decision: solve `tiers` in order (one placer
    call each), announce `blocked` (QuotaExceeded), keep `priorities` for
    the preemption planner that runs after the solve."""

    tiers: List[list] = field(default_factory=list)
    blocked: List[Tuple[object, str, str]] = field(default_factory=list)
    priorities: Dict[str, int] = field(default_factory=dict)
    # Keys admitted through the starvation guard this cycle: the gang
    # scheduler stamps `starvation_promoted` on them at admission, which
    # shields them from the preemption planner (aging = priority boost,
    # and a boost that evaporated at admission would be no guard at all).
    starved: set = field(default_factory=set)


@dataclass
class PreemptionDecision:
    victim_key: str  # "ns/name" of the displaced PodGroup
    preemptor_key: str  # "ns/name" of the gang that needed the capacity
    queue: str  # victim's queue name ("" = unqueued)
    reason: str


class TenancyArbiter:
    """The arbiter one GangScheduler consults each solve cycle. Reads the
    tenancy kinds from the store per cycle via `list_refs` (frozen
    references; the populations are tiny), so it needs no informer of its
    own and a quota edit is honored on the very next solve."""

    def __init__(
        self,
        api,
        now_fn,
        starvation_seconds: float = 600.0,
        max_preemptions: int = 3,
    ):
        self.api = api
        self.now = now_fn
        self.starvation_seconds = starvation_seconds
        self.max_preemptions = max_preemptions
        # One tenancy load per solve cycle: arbitrate() refreshes it and the
        # preemption planner (which runs later in the SAME single-threaded
        # cycle) reuses it, so admission and victim selection can never read
        # two different quota/class catalogs within one cycle.
        self._cycle_load: Optional[
            Tuple[Dict[str, ClusterQueue], Dict[str, PriorityClass]]
        ] = None

    # -- store views ---------------------------------------------------

    def _load(self) -> Tuple[Dict[str, ClusterQueue], Dict[str, PriorityClass]]:
        queues = {q.metadata.name: q for q in self.api.list_refs("ClusterQueue")}
        classes = {c.metadata.name: c for c in self.api.list_refs("PriorityClass")}
        return queues, classes

    # -- admission -----------------------------------------------------

    def arbitrate(
        self, requests: List, groups: Iterable[PodGroup], now: float
    ) -> Arbitration:
        """Order + quota-filter one cycle's pending GangRequests. `groups`
        is the gang scheduler's full PodGroup view (admitted usage is
        derived from it); requests not in the result's tiers are in
        `blocked` and stay Pending.

        Incremental solving hands a DIRTY SUBSET as `requests` — the quota
        gate still admits against the full admitted usage (from `groups`),
        and tiers with no dirty members simply produce no placer call; a
        capacity-freeing event always escalates the scheduler back to the
        full pending set, so a freed window re-opens lower tiers in the
        same arbiter order as before."""
        queues, classes = self._load()
        self._cycle_load = (queues, classes)
        usage = admitted_usage(groups, queues)
        out = Arbitration()

        # Bucket candidates: starved gangs FIFO at the very front (the
        # starvation guard outranks priority, never quota), the rest into
        # (priority, queue) lines with preempted gangs (fair-share debt)
        # at the front of their queue's line.
        starved: List[Tuple[float, str, object, Optional[ClusterQueue]]] = []
        lines: Dict[int, Dict[str, List]] = {}
        line_queue: Dict[str, Optional[ClusterQueue]] = {}
        for req in requests:
            pg = req.group
            prio, _ = resolve_priority(pg, classes)
            out.priorities[req.key] = prio
            q = queue_for_group(pg, queues)
            if pg.queue and q is None and queues:
                # A named queue that doesn't exist is a wait, not a bypass
                # (kueue semantics; a typo must not skip the quota gate).
                out.blocked.append(
                    (req, pg.queue, f"queue {pg.queue!r} does not exist")
                )
                continue
            created = pg.metadata.creation_time
            # No birth stamp = no measurable wait: never "starved" (on a
            # wall clock the or-zero fallback would read as an epoch-long
            # wait and promote EVERYTHING, silently disabling priority).
            if (
                self.starvation_seconds > 0
                and created is not None
                and now - created > self.starvation_seconds
            ):
                starved.append((created, pg.metadata.name, req, q))
                continue
            qname = q.name if q is not None else ""
            line_queue[qname] = q
            lines.setdefault(prio, {}).setdefault(qname, []).append(req)

        def debt_key(req):
            pg = req.group
            # Displaced gangs first (oldest debt first), then FIFO.
            return (
                0 if pg.preemption_count > 0 else 1,
                pg.last_preempted_at,
                pg.metadata.creation_time or 0.0,
                pg.metadata.name,
            )

        def admit(req, q: Optional[ClusterQueue], tier: List) -> None:
            demand = req.group.min_resources or {}
            if q is not None:
                over = sorted(
                    res
                    for res in q.quota
                    if usage.get(q.name, {}).get(res, 0.0) + demand.get(res, 0.0)
                    > q.cap(res) + _EPS
                )
                if over:
                    out.blocked.append((
                        req, q.name,
                        f"queue {q.name!r} quota exhausted for "
                        + ", ".join(over),
                    ))
                    return
                bucket = usage.setdefault(q.name, {})
                for res, val in demand.items():
                    bucket[res] = bucket.get(res, 0.0) + val
            tier.append(req)

        if starved:
            tier: List = []
            for _, _, req, q in sorted(starved, key=lambda s: (s[0], s[1])):
                admit(req, q, tier)
            out.starved.update(req.key for req in tier)
            if tier:
                out.tiers.append(tier)

        for prio in sorted(lines, reverse=True):
            per_queue = {
                qname: sorted(reqs, key=debt_key)
                for qname, reqs in lines[prio].items()
            }
            tier = []
            # Round-robin by ascending weighted dominant share, recomputed
            # after every admission so queues interleave instead of one
            # queue drained first (the fairness the Jain bench measures).
            while per_queue:
                def share_of(qname: str) -> float:
                    q = line_queue[qname]
                    if q is None:
                        return 0.0
                    return dominant_share(q, usage.get(qname, {}))

                qname = min(per_queue, key=lambda n: (share_of(n), n))
                req = per_queue[qname].pop(0)
                if not per_queue[qname]:
                    del per_queue[qname]
                admit(req, line_queue[qname], tier)
            if tier:
                out.tiers.append(tier)
        return out

    # -- preemption ----------------------------------------------------

    def _eligible_victims(
        self, req, prio: int, can_preempt_lower: bool, reclaiming: bool,
        admitted: List[PodGroup], classes, queues, usage, taken: set,
    ) -> Dict[str, Tuple[PodGroup, int, float, str]]:
        """vkey -> (victim, its priority, its chip cost, its queue) for one
        preemptor. Eligibility: strictly lower priority (when the
        preemptor's class may preempt), or — on the reclaim arm — a
        borrower at <= the preemptor's priority. Gangs at their preemption
        cap or admitted via the starvation guard are immune."""
        from training_operator_tpu.cluster.inventory import TPU_RESOURCE

        out: Dict[str, Tuple[PodGroup, int, float, str]] = {}
        for vic in admitted:
            vkey = f"{vic.namespace}/{vic.name}"
            if vkey in taken or vkey == req.key:
                continue
            if vic.preemption_count >= self.max_preemptions:
                continue  # displaced enough: immune now
            if vic.starvation_promoted:
                # Admitted through the starvation guard: evicting it would
                # undo the promotion the guard exists to make.
                continue
            vprio, _ = resolve_priority(vic, classes)
            vq = queue_for_group(vic, queues)
            borrower = vq is not None and any(
                usage.get(vq.name, {}).get(res, 0.0)
                > vq.quota.get(res, 0.0) + _EPS
                for res in vq.quota
            )
            if not (
                (can_preempt_lower and vprio < prio)
                or (reclaiming and borrower and vprio <= prio)
            ):
                continue
            vres = vic.min_resources or {}
            cost = vres.get(TPU_RESOURCE, 0.0) or sum(vres.values())
            out[vkey] = (vic, vprio, cost, vq.name if vq is not None else "")
        return out

    _BLOCKED = object()  # host held by a non-evictable occupant

    def _tpu_slice_plan(
        self, req, eligible, snapshot, claimed_hosts: set,
    ) -> Optional[Tuple[set, set]]:
        """Topology-aware victim selection for a TPU preemptor: find, per
        needed slice, the CHEAPEST contiguous host window of the right
        size whose occupants are all evictable (or already free) —
        freeing chips that don't form an ICI block would displace work
        for nothing (the exact thrash chip-counting produces; the bench
        caught it). Returns (victim keys, window host nodes) or None when
        no covering set of windows exists."""
        from training_operator_tpu.scheduler.snapshot import (
            request_hosts_per_slice,
        )

        want_slices = max(1, req.num_slices)
        owner: Dict[str, str] = {}
        for vkey, (vic, _vprio, _cost, _vq) in eligible.items():
            for node in set(vic.placement.values()) | set(vic.reserved_nodes):
                owner[node] = vkey
        plans = []  # (max victim prio, chip cost, slice id, victims, hosts)
        for sid in sorted(snapshot.slices):
            sl = snapshot.slices[sid]
            h = request_hosts_per_slice(req, sl.chips_per_host)
            if h <= 0 or h > sl.num_hosts:
                continue
            states = []
            for node in sl.host_nodes:
                if node in claimed_hosts:
                    states.append(self._BLOCKED)  # promised to an earlier plan
                elif snapshot.host_free(node, sl.chips_per_host):
                    states.append(None)
                elif node in owner:
                    states.append(owner[node])
                else:
                    states.append(self._BLOCKED)
            best = None
            for start in range(sl.num_hosts - h + 1):
                window = states[start:start + h]
                if any(s is self._BLOCKED for s in window):
                    continue
                vks = {s for s in window if s is not None}
                cost = sum(eligible[v][2] for v in vks)
                max_prio = max(
                    (eligible[v][1] for v in vks), default=-(10 ** 12)
                )
                key = (max_prio, cost, start)
                if best is None or key < best[0]:
                    best = (key, vks, set(sl.host_nodes[start:start + h]))
            if best is not None:
                plans.append(
                    (best[0][0], best[0][1], sid, best[1], best[2])
                )
        # Cheapest slices first: lowest victim priority, then chip cost.
        plans.sort(key=lambda p: (p[0], p[1], p[2]))
        if len(plans) < want_slices:
            return None
        victims: set = set()
        hosts: set = set()
        for _, _, _, vks, window_hosts in plans[:want_slices]:
            victims.update(vks)
            hosts.update(window_hosts)
        return victims, hosts

    def _generic_plan(
        self, req, eligible, snapshot, freed: Dict[str, float],
    ) -> Optional[set]:
        """Chip-deficit victim selection for non-TPU preemptors: cheapest
        first until every short resource is covered."""
        demand = {
            res: val for res, val in (req.group.min_resources or {}).items()
            if val > 0
        }
        if not demand:
            return None
        free: Dict[str, float] = {}
        for avail in snapshot.free.values():
            for res, val in avail.items():
                if val > 0:
                    free[res] = free.get(res, 0.0) + val
        deficit = {}
        for res, need in demand.items():
            short = need - free.get(res, 0.0) - freed.get(res, 0.0)
            if short > _EPS:
                deficit[res] = short
        if not deficit:
            return None  # fragmentation-only: eviction can't be shown to help
        candidates = sorted(
            eligible.items(),
            key=lambda kv: (
                kv[1][1],  # lowest priority first
                kv[1][2],  # then least displaced work
                -(kv[1][0].metadata.creation_time or 0.0),  # youngest
                kv[0],
            ),
        )
        chosen: set = set()
        got: Dict[str, float] = {}
        for vkey, (vic, _vprio, _cost, _vq) in candidates:
            if all(got.get(r, 0.0) >= s - _EPS for r, s in deficit.items()):
                break
            vres = vic.min_resources or {}
            if all(
                got.get(r, 0.0) >= deficit[r] - _EPS or vres.get(r, 0.0) <= _EPS
                for r in deficit
            ):
                continue  # contributes nothing still missing
            chosen.add(vkey)
            for r in deficit:
                got[r] = got.get(r, 0.0) + vres.get(r, 0.0)
        if not chosen or not all(
            got.get(r, 0.0) >= s - _EPS for r, s in deficit.items()
        ):
            return None  # no covering plan: don't evict futilely
        return chosen

    def plan_preemptions(
        self,
        unplaced: List,
        priorities: Dict[str, int],
        groups: Iterable[PodGroup],
        snapshot,
        now: float,
    ) -> List[PreemptionDecision]:
        """Victims for the gangs the solve could not place. A plan frees
        whole admitted gangs (a gang is the eviction unit — partial
        eviction would just break the victim's own ICI mesh), and is only
        returned when it provably covers the preemptor: a contiguous host
        window per needed slice for TPU gangs, the chip deficit for
        generic ones. The gang scheduler executes decisions and re-solves
        in the SAME cycle, so freed capacity goes to the preemptor before
        any lower tier can backfill it."""
        if not unplaced:
            return []
        # Same-cycle catalog: set by this cycle's arbitrate(). Fresh load
        # only when the planner is driven standalone (tests, tools).
        queues, classes = self._cycle_load or self._load()
        groups = list(groups)
        usage = admitted_usage(groups, queues)
        admitted = [pg for pg in groups if pg.phase in ADMITTED_PHASES]
        decisions: List[PreemptionDecision] = []
        taken: set = set()
        claimed_hosts: set = set()
        freed: Dict[str, float] = {}

        order = sorted(
            unplaced,
            key=lambda r: (
                -priorities.get(r.key, 0),
                r.group.metadata.creation_time or 0.0,
                r.group.metadata.name,
            ),
        )
        for req in order:
            pg = req.group
            prio, policy = resolve_priority(pg, classes)
            # A Never class blocks the PRIORITY arm only; quota reclaim is
            # a queue-level right (kueue's reclaimWithinCohort), not a
            # class privilege — a quota'd team must be able to take its
            # nominal share back from borrowers regardless of class.
            can_preempt_lower = policy != PREEMPTION_NEVER
            q = queue_for_group(pg, queues)
            demand = pg.min_resources or {}
            # Reclaim arm: a queue asking for no more than its NOMINAL
            # quota may displace borrowers of any queue at <= its priority.
            reclaiming = False
            if q is not None and q.quota:
                reclaiming = all(
                    usage.get(q.name, {}).get(res, 0.0) + demand.get(res, 0.0)
                    <= q.quota.get(res, 0.0) + _EPS
                    for res in q.quota
                )
            eligible = self._eligible_victims(
                req, prio, can_preempt_lower, reclaiming,
                admitted, classes, queues, usage, taken,
            )
            if not eligible:
                continue
            if req.is_tpu():
                plan = self._tpu_slice_plan(req, eligible, snapshot,
                                            claimed_hosts)
                if plan is None:
                    continue
                chosen, window_hosts = plan
                if not chosen:
                    # A free window already exists: the preemptor lost it
                    # to same-tier competition, not to lower-priority work
                    # — nothing to evict.
                    continue
                claimed_hosts.update(window_hosts)
            else:
                chosen = self._generic_plan(req, eligible, snapshot, freed)
                if not chosen:
                    continue
            for vkey in sorted(chosen):
                vic, _vprio, _cost, vqueue = eligible[vkey]
                taken.add(vkey)
                if vqueue and vqueue in usage:
                    # Keep the accounting live as victims are taken: a
                    # queue that stops borrowing the moment its gang is
                    # planned for eviction must not still read as a
                    # borrower to the NEXT preemptor's reclaim arm.
                    bucket = usage[vqueue]
                    for res, val in (vic.min_resources or {}).items():
                        bucket[res] = max(0.0, bucket.get(res, 0.0) - val)
                for res, val in (vic.min_resources or {}).items():
                    freed[res] = freed.get(res, 0.0) + val
                decisions.append(PreemptionDecision(
                    victim_key=vkey,
                    preemptor_key=req.key,
                    queue=vqueue,
                    reason=(
                        f"higher-priority gang {req.key} "
                        f"(priority {prio}) needs capacity"
                    ),
                ))
            if q is not None:
                # The preemptor will take the freed capacity at the
                # same-cycle re-solve: charge its demand now so a LATER
                # same-queue preemptor's reclaim test sees the joint
                # demand (two gangs each within nominal quota must not
                # both claim the <=-priority reclaim right when together
                # they exceed it).
                bucket = usage.setdefault(q.name, {})
                for res, val in demand.items():
                    bucket[res] = bucket.get(res, 0.0) + val
        return decisions


def preempt_pod(api, pod, reason: str, now: float) -> bool:
    """Fail one member pod of a preempted gang — the tenancy twin of
    nodelifecycle.evict_pod (both ride fail_pod, the one shared fail-a-pod
    sequence), with the PREEMPTED marker the engine's triage treats as
    retryable WITHOUT charging the restart budget (the workload did
    nothing wrong; the fleet took its hardware back). Returns False when
    the pod is already terminal or deleted."""
    from training_operator_tpu.controllers.nodelifecycle import fail_pod

    return fail_pod(
        api, pod, PREEMPTED_MESSAGE_PREFIX, reason, now,
        event_reason="Preempted", event_verb="preempted",
    ) is not None
