"""Job-lifecycle span tracer: per-job phase timelines on the API server.

Dapper-style per-request tracing applied to the TrainJob lifecycle
(SURVEY §5: the reference's only observability is flat counters plus k8s
Events — nobody can answer "where did this job spend its time: admission,
queue, gang solve, bind, or container start?" without reading logs).

The model is deliberately small:

- A `Span` is a named interval on one job's timeline. `start`/`end` are
  cluster-clock timestamps (comparable with job conditions and Events);
  `wall` carries the REAL elapsed seconds where the measurement is a wall
  quantity (solver time, queue wait) — on a virtual clock start == end for
  instantaneous work, and `wall` is then the truthful duration.
- A `JobTimeline` is a bounded ring of completed spans plus first-wins
  `marks` (named instants), keyed by (namespace, name). Span `uid` attrs
  distinguish incarnations of a recreated name; the timeline itself is NOT
  reset on uid change — a TrainJob and the workload job it owns share a
  name on purpose, and their spans interleave into one lifecycle view.
- A `TimelineStore` holds one timeline per job in an LRU ring (oldest job
  evicted past `max_jobs`), with an injected clock so virtual-clock
  simulations trace in simulated time.

Everything here is dependency-free (no cluster imports): the APIServer
owns a store instance, and instrumentation sites reach it as
`api.timelines`. Tracing can be disabled process-wide (`set_enabled`) —
the bench's `observe` block measures that the instrumented hot paths stay
within 5% of the disabled run.
"""

from __future__ import annotations

import threading
import time as _time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from training_operator_tpu.utils.locks import TrackedLock

# Process-wide master switch, consulted by every record/mark call. Module
# attribute (not config) so the bench and tests can flip it without
# plumbing; per-store `enabled` composes with it.
_ENABLED = True


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def enabled() -> bool:
    return _ENABLED


@dataclass
class Span:
    """One completed interval of a job's lifecycle."""

    name: str
    start: float
    end: float
    # Real elapsed seconds when the measurement is a wall quantity (queue
    # wait, solver time); 0.0 means "end - start is the duration".
    wall: float = 0.0
    attrs: Dict[str, Any] = field(default_factory=dict)

    def duration(self) -> float:
        """The truthful duration: wall where recorded, else end - start."""
        return self.wall if self.wall > 0.0 else max(0.0, self.end - self.start)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "wall": self.wall,
            "attrs": dict(self.attrs),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Span":
        return cls(
            name=str(d.get("name", "")),
            start=float(d.get("start", 0.0)),
            end=float(d.get("end", 0.0)),
            wall=float(d.get("wall", 0.0)),
            attrs=dict(d.get("attrs", {})),
        )


class JobTimeline:
    """Bounded span ring + first-wins marks for one (namespace, name)."""

    def __init__(self, namespace: str, name: str, max_spans: int = 256):
        self.namespace = namespace
        self.name = name
        self.uids: List[str] = []  # insertion order, first = original
        self.spans: "deque[Span]" = deque(maxlen=max_spans)
        self.marks: Dict[str, float] = {}

    def sorted_spans(self) -> List[Span]:
        """Spans in timeline order (start, then end) — recording order is
        arrival order across components, not time order."""
        return sorted(self.spans, key=lambda s: (s.start, s.end, s.name))

    def to_dict(self) -> Dict[str, Any]:
        return {
            "namespace": self.namespace,
            "name": self.name,
            "uids": list(self.uids),
            "spans": [s.to_dict() for s in self.sorted_spans()],
            "marks": dict(self.marks),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any], max_spans: int = 256) -> "JobTimeline":
        tl = cls(d.get("namespace", ""), d.get("name", ""), max_spans=max_spans)
        tl.uids = list(d.get("uids", []))
        for sd in d.get("spans", []):
            tl.spans.append(Span.from_dict(sd))
        tl.marks = {str(k): float(v) for k, v in d.get("marks", {}).items()}
        return tl


class TimelineStore:
    """LRU ring of JobTimelines, keyed by (namespace, name).

    Thread-safe: instrumentation records from API handler threads, manager
    worker pools, and the scheduler tick concurrently. `now_fn` is the
    injected cluster clock (Cluster wires its own in; the host role's
    WallClock makes timestamps restart-comparable)."""

    def __init__(self, now_fn=None, max_jobs: int = 512, max_spans: int = 256):
        self._now = now_fn or _time.time
        self.max_jobs = max_jobs
        self.max_spans = max_spans
        self.enabled = True
        self._jobs: "OrderedDict[tuple, JobTimeline]" = OrderedDict()
        self._lock = TrackedLock("timeline")

    def set_clock(self, now_fn) -> None:
        self._now = now_fn

    def now(self) -> float:
        return self._now()

    def count(self) -> int:
        """Timelines currently retained (LRU-bounded by max_jobs) — the
        INV009 accumulator feed."""
        with self._lock:
            return len(self._jobs)

    def _timeline_locked(self, namespace: str, name: str) -> JobTimeline:
        key = (namespace or "", name)
        tl = self._jobs.get(key)
        if tl is None:
            tl = self._jobs[key] = JobTimeline(
                namespace or "", name, max_spans=self.max_spans
            )
        self._jobs.move_to_end(key)
        while len(self._jobs) > self.max_jobs:
            self._jobs.popitem(last=False)
        return tl

    # Incarnation history cap: a name resubmitted forever (nightly jobs)
    # must not grow its uid list unboundedly — keep the first + recent.
    MAX_UIDS = 8

    @classmethod
    def _note_uid(cls, tl: JobTimeline, uid: str) -> None:
        if not uid or uid in tl.uids:
            return
        if len(tl.uids) >= cls.MAX_UIDS:
            tl.uids = [tl.uids[0], *tl.uids[-(cls.MAX_UIDS - 2):]]
        tl.uids.append(uid)

    def record_span(
        self,
        namespace: str,
        name: str,
        uid: str,
        span_name: str,
        start: Optional[float] = None,
        end: Optional[float] = None,
        wall: float = 0.0,
        attrs: Optional[Dict[str, Any]] = None,
        **extra: Any,
    ) -> None:
        """Record one completed span. `start`/`end` default to now; `wall`
        carries the real elapsed seconds where that is the measurement.
        Attributes ride either as keywords (trusted call sites) or via
        `attrs` — the wire ingest path, where client-chosen keys must not
        collide with this signature."""
        if not (_ENABLED and self.enabled):
            return
        t = None
        if start is None or end is None:
            t = self._now()
        merged = {**(attrs or {}), **extra}
        span = Span(
            span_name,
            t if start is None else start,
            t if end is None else end,
            wall=wall,
            attrs=merged,
        )
        if uid:
            span.attrs.setdefault("uid", uid)
        with self._lock:
            tl = self._timeline_locked(namespace, name)
            self._note_uid(tl, uid)
            tl.spans.append(span)

    def mark(
        self, namespace: str, name: str, uid: str, mark_name: str,
        t: Optional[float] = None,
    ) -> None:
        """First-wins named instant (e.g. "created", "running")."""
        if not (_ENABLED and self.enabled):
            return
        if t is None:
            t = self._now()
        with self._lock:
            tl = self._timeline_locked(namespace, name)
            self._note_uid(tl, uid)
            tl.marks.setdefault(mark_name, t)

    def timeline(self, namespace: str, name: str) -> Optional[JobTimeline]:
        with self._lock:
            return self._jobs.get((namespace or "", name))

    def timelines(self) -> List[JobTimeline]:
        with self._lock:
            return list(self._jobs.values())

    def forget(self, namespace: str, name: str) -> None:
        with self._lock:
            self._jobs.pop((namespace or "", name), None)
