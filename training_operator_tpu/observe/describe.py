"""kubectl-describe analogue: one job's conditions, Events, and phase table.

`render_describe(api, namespace, name)` works against any APIServer
duck-type — the in-process store, or a `RemoteAPIServer` pointed at a
serving host — and is what `python -m training_operator_tpu describe`
prints. Three sections:

  Conditions  condition history from job status (type/status/reason/age)
  Events      the job's Event stream (uniform lifecycle events from the
              controller path + gang scheduler warnings)
  Phases      durations aggregated from the job's timeline ring
              (observe/timeline.py): where the job spent its time —
              admission, workqueue wait, gang solve, bind, reconcile,
              submit->Running, submit->terminal.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# Canonical phase order for the table; unknown span names follow sorted.
PHASE_ORDER = (
    "admission",
    "queue_wait",
    "reconcile",
    "gang_solve",
    "bind",
    "node_evict",
    "preempt",
    "time_to_running",
    "total",
)


def find_job(api, namespace: str, name: str) -> Optional[Any]:
    """Probe every job kind (v2 TrainJob first — it owns same-named
    workload jobs) for namespace/name."""
    from training_operator_tpu.api.jobs import JOB_KINDS

    for kind in ("TrainJob", *JOB_KINDS):
        obj = api.try_get(kind, namespace, name)
        if obj is not None:
            return obj
    return None


def _conditions(job) -> List[Tuple[str, str, str, float, str]]:
    """(type, status, reason, transition_time, message) rows from either a
    v1 JobStatus or a v2 TrainJob condition list."""
    status = getattr(job, "status", None)
    conds = list(getattr(status, "conditions", []) or [])
    rows = []
    for c in sorted(conds, key=lambda c: getattr(c, "last_transition_time", 0.0)):
        ctype = getattr(c.type, "value", c.type)
        rows.append((
            str(ctype),
            "True" if c.status else "False",
            c.reason,
            getattr(c, "last_transition_time", 0.0),
            c.message,
        ))
    return rows


def phase_table(timeline: Optional[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Aggregate a wire-shaped timeline dict into per-phase rows:
    {phase, count, total_s, first_start, last_end}."""
    if not timeline:
        return []
    agg: Dict[str, Dict[str, Any]] = {}
    for span in timeline.get("spans", []):
        name = span.get("name", "")
        wall = float(span.get("wall", 0.0))
        start = float(span.get("start", 0.0))
        end = float(span.get("end", 0.0))
        dur = wall if wall > 0.0 else max(0.0, end - start)
        row = agg.setdefault(
            name,
            {"phase": name, "count": 0, "total_s": 0.0,
             "first_start": start, "last_end": end},
        )
        row["count"] += 1
        row["total_s"] += dur
        row["first_start"] = min(row["first_start"], start)
        row["last_end"] = max(row["last_end"], end)
    order = {p: i for i, p in enumerate(PHASE_ORDER)}
    return sorted(
        agg.values(), key=lambda r: (order.get(r["phase"], len(order)), r["phase"])
    )


def _node_state(node) -> str:
    """One-word node condition summary for the pod table: Ready/NotReady
    from the Ready condition (controllers/nodelifecycle.py), with the
    cordon flag appended kubectl-style."""
    if node is None:
        return "<gone>"
    from training_operator_tpu.cluster.objects import node_ready

    state = "Ready" if node_ready(node) else "NotReady"
    if node.unschedulable:
        state += ",SchedulingDisabled"
    return state


def _pod_rows(api, namespace: str, name: str) -> List[Tuple[str, str, str, str]]:
    """(pod, phase, node, node state) per pod of the job — where each pod
    physically sits, and whether that hardware is alive. This is the
    surface a node-loss investigation starts from."""
    from training_operator_tpu.api.common import JOB_NAME_LABEL

    rows = []
    nodes: Dict[str, Any] = {}
    for pod in sorted(
        api.list("Pod", namespace or None, {JOB_NAME_LABEL: name}),
        key=lambda p: p.name,
    ):
        node_name = pod.node_name or "<unbound>"
        state = ""
        if pod.node_name:
            if pod.node_name not in nodes:
                nodes[pod.node_name] = api.try_get("Node", "", pod.node_name)
            state = _node_state(nodes[pod.node_name])
        rows.append((pod.name, pod.status.phase.value, node_name, state))
    return rows


def _get_timeline(api, namespace: str, name: str) -> Optional[Dict[str, Any]]:
    getter = getattr(api, "get_timeline", None)
    if getter is None:
        return None
    return getter(namespace, name)


def render_describe(api, namespace: str, name: str, max_events: int = 40) -> str:
    """The full describe document as a string (raises NotFoundError-shaped
    ValueError when no job kind matches)."""
    job = find_job(api, namespace, name)
    if job is None:
        raise ValueError(f"no job of any known kind named {namespace}/{name}")

    lines: List[str] = []
    meta = job.metadata
    lines.append(f"Name:         {meta.name}")
    lines.append(f"Namespace:    {meta.namespace or ''}")
    lines.append(f"Kind:         {job.KIND}")
    lines.append(f"UID:          {meta.uid or ''}")
    if meta.creation_time is not None:
        lines.append(f"Created:      t={meta.creation_time:.3f}")

    lines.append("")
    lines.append("Conditions:")
    rows = _conditions(job)
    if rows:
        lines.append(f"  {'TYPE':<12} {'STATUS':<7} {'REASON':<24} {'AT':>12}  MESSAGE")
        for ctype, status, reason, at, message in rows:
            lines.append(
                f"  {ctype:<12} {status:<7} {reason:<24} {at:>12.3f}  {message}"
            )
    else:
        lines.append("  <none>")

    pg = api.try_get("PodGroup", namespace, name)
    if pg is not None:
        lines.append("")
        lines.append("Gang:")
        phase = getattr(pg.phase, "value", str(pg.phase))
        prio = ""
        if pg.priority_class:
            pc = api.try_get("PriorityClass", "", pg.priority_class)
            prio = f"  PriorityClass: {pg.priority_class}"
            if pc is not None:
                prio += f" (value {pc.value})"
            else:
                prio += " (NOT FOUND)"
        lines.append(
            f"  Phase: {phase}  Queue: {pg.queue or '<none>'}{prio}"
        )
        if pg.preemption_count or pg.checkpointed_seconds:
            lines.append(
                f"  Preemptions: {pg.preemption_count}  "
                f"Checkpointed: {pg.checkpointed_seconds:.1f}s "
                f"(resumes from step, not step 0)"
            )

    lines.append("")
    lines.append("Pods:")
    pod_rows = _pod_rows(api, namespace, name)
    if pod_rows:
        lines.append(f"  {'NAME':<28} {'PHASE':<10} {'NODE':<20} NODE-STATE")
        for pname, phase, node_name, state in pod_rows:
            lines.append(f"  {pname:<28} {phase:<10} {node_name:<20} {state}")
    else:
        lines.append("  <none>")

    lines.append("")
    lines.append("Events:")
    events = [
        e for e in api.events(object_name=name)
        if (e.namespace or "") == (namespace or "")
    ]
    events.sort(key=lambda e: e.timestamp)
    if events:
        lines.append(f"  {'AT':>12}  {'TYPE':<8} {'KIND':<10} {'REASON':<22} MESSAGE")
        for e in events[-max_events:]:
            # Aggregated repeats (k8s parity): one row with a count, the
            # kubectl `(x12 over 5m)` shape.
            count = getattr(e, "count", 1)
            suffix = f" (x{count})" if count > 1 else ""
            lines.append(
                f"  {e.timestamp:>12.3f}  {e.event_type:<8} {e.object_kind:<10} "
                f"{e.reason:<22} {e.message}{suffix}"
            )
    else:
        lines.append("  <none>")

    lines.append("")
    lines.append("Phases (from timeline ring):")
    table = phase_table(_get_timeline(api, namespace, name))
    if table:
        lines.append(f"  {'PHASE':<18} {'COUNT':>5} {'TOTAL_S':>12} {'FIRST':>12} {'LAST':>12}")
        for row in table:
            lines.append(
                f"  {row['phase']:<18} {row['count']:>5} {row['total_s']:>12.6f} "
                f"{row['first_start']:>12.3f} {row['last_end']:>12.3f}"
            )
    else:
        lines.append("  <no timeline recorded (tracing disabled, or job predates the ring)>")
    return "\n".join(lines)
