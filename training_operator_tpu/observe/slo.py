"""SLO engine: objectives, multi-window burn-rate evaluation, incidents.

The PR 4 timelines and PR 7 fleet gauges record raw durations; nothing in
the system knew what "good" is or whether it is being attained. This module
closes the read side of ROADMAP item 3: a cluster-scoped `SLOPolicy` kind
declares per-queue/per-kind objectives over the two latencies users feel —
`time_to_running` (creation -> Running condition) and `queue_wait` (manager
workqueue enqueue -> pop) — and `SLOEvaluator` scores them against the
sliding-window histogram feeds (utils/metrics.py slo_*_window families) the
engine/controller transition paths populate.

Burn-rate semantics follow multi-window Prometheus/SRE practice:

  bad_fraction(w) = 1 - good(w) / count(w)        over window w
  burn_rate(w)    = bad_fraction(w) / (1 - target)

where `good` counts observations <= the objective's threshold (linear
interpolation inside the straddling bucket; observations beyond the last
finite bucket bound are scored bad — conservative). An objective is BURNING
only when BOTH the fast and slow windows exceed `burn_threshold`: the fast
window makes detection prompt, the slow window keeps a brief spike from
paging. Each evaluation republishes:

  training_slo_attainment_ratio{policy,objective,queue}   good fraction, slow window
  training_slo_budget_remaining{policy,objective,queue}   1 - burn_slow, clamped to [0,1]
  training_slo_burn_rate{policy,objective,queue,window}   per window (fast | slow)

and emits ONE aggregated `SLOBurnRate` Warning Event per incident (the
not-burning -> burning transition), k8s-events style: a breach persisting
across evaluations is one incident, not one event per pass. The returned
section dict is the `slo` block `GET /fleet` / `top` render, including the
per-queue aggregate attribution shares (observe/attribution.py) the item-3
autoscaler will consume.

SLOPolicy is cluster-scoped and pinned to the meta store shard exactly like
PriorityClass (cluster/shards.py CLUSTER_SCOPED_KINDS), so a sharded
control plane evaluates one policy catalog, not N drifting ones.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from training_operator_tpu.api.jobs import ObjectMeta
from training_operator_tpu.utils import metrics

# Objective metric names -> the windowed family each is scored against.
SLO_METRICS: Dict[str, Any] = {
    "time_to_running": metrics.slo_time_to_running_window,
    "queue_wait": metrics.slo_queue_wait_window,
}

# Default multi-window pair (5m fast / 1h slow — the classic page-window
# shape, sized to the windowed families' 4h retention).
DEFAULT_FAST_WINDOW = 300.0
DEFAULT_SLOW_WINDOW = 3600.0


@dataclass
class SLOObjective:
    """One scored objective inside an SLOPolicy.

    `target` is the attainment goal (0.99 = 99% of observations within
    `threshold_seconds`); the error budget is `1 - target`. Empty `queue` /
    `kind` selectors match every queue / job kind (children are merged
    before scoring, so an all-queues objective scores the union, not the
    per-queue mean)."""

    name: str = ""
    metric: str = "time_to_running"
    threshold_seconds: float = 0.0
    target: float = 0.99
    queue: str = ""
    kind: str = ""
    fast_window_seconds: float = DEFAULT_FAST_WINDOW
    slow_window_seconds: float = DEFAULT_SLOW_WINDOW
    burn_threshold: float = 1.0


@dataclass
class SLOPolicy:
    """Cluster-scoped bundle of objectives (one team/fleet SLO document)."""

    KIND = "SLOPolicy"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    objectives: List[SLOObjective] = field(default_factory=list)
    description: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return ""


def validate_slo_policy(policy: SLOPolicy) -> None:
    from training_operator_tpu.api.validation import (
        ValidationError,
        is_dns1035_label,
    )

    errs: List[str] = []
    if not policy.metadata.name:
        errs.append("metadata.name: required")
    elif not is_dns1035_label(policy.metadata.name):
        errs.append(
            f"metadata.name: {policy.metadata.name!r} is not a DNS-1035 label"
        )
    if not policy.objectives:
        errs.append("objectives: at least one objective is required")
    seen: set = set()
    for i, obj in enumerate(policy.objectives):
        where = f"objectives[{i}]"
        if not obj.name:
            errs.append(f"{where}.name: required")
        elif obj.name in seen:
            errs.append(f"{where}.name: duplicate objective name {obj.name!r}")
        else:
            seen.add(obj.name)
        if obj.metric not in SLO_METRICS:
            errs.append(
                f"{where}.metric: {obj.metric!r} must be one of "
                f"{sorted(SLO_METRICS)}"
            )
        if not obj.threshold_seconds > 0:
            errs.append(
                f"{where}.thresholdSeconds: {obj.threshold_seconds} must be > 0"
            )
        if not 0.0 < obj.target < 1.0:
            errs.append(
                f"{where}.target: {obj.target} must be strictly between 0 and 1"
            )
        if not obj.fast_window_seconds > 0:
            errs.append(
                f"{where}.fastWindowSeconds: {obj.fast_window_seconds} must be > 0"
            )
        if not obj.slow_window_seconds > obj.fast_window_seconds:
            errs.append(
                f"{where}.slowWindowSeconds: {obj.slow_window_seconds} must "
                f"exceed fastWindowSeconds ({obj.fast_window_seconds})"
            )
        if not obj.burn_threshold > 0:
            errs.append(
                f"{where}.burnThreshold: {obj.burn_threshold} must be > 0"
            )
    if errs:
        raise ValidationError(errs)


def _admit_slo_policy(policy: SLOPolicy) -> None:
    # Cluster-scoped: namespace "" like PriorityClass, so the shard map and
    # every lookup path agree on the key.
    policy.metadata.namespace = ""
    validate_slo_policy(policy)


def register_slo_admission(api) -> None:
    """Admission for SLOPolicy, on whichever APIServer stores it (a
    malformed policy must not wedge the evaluator mid-fleet)."""
    api.register_admission(SLOPolicy.KIND, _admit_slo_policy)


# ---------------------------------------------------------------------------
# Burn-rate evaluation
# ---------------------------------------------------------------------------


def _merge_views(views: List[List[Tuple[float, int]]]) -> List[Tuple[float, int]]:
    """Sum same-layout cumulative bucket views (children of one family all
    share the family's bucket tuple, so positional merge is exact)."""
    if not views:
        return []
    if len(views) == 1:
        return views[0]
    merged = [[bound, 0] for bound, _ in views[0]]
    for view in views:
        for i, (_, cum) in enumerate(view):
            merged[i][1] += cum
    return [(bound, cum) for bound, cum in merged]


def _good_count(view: List[Tuple[float, int]], threshold: float) -> float:
    """Observations <= threshold, from a cumulative bucket view. Linear
    interpolation inside the straddling bucket (Prometheus histogram_quantile
    convention); thresholds past the last finite bound score only the finite
    buckets as good — the +Inf residue is conservatively bad."""
    if not view:
        return 0.0
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in view:
        if bound == float("inf"):
            return float(prev_cum)
        if threshold <= bound:
            if threshold == bound or bound <= prev_bound:
                return float(cum)
            frac = (threshold - prev_bound) / (bound - prev_bound)
            return prev_cum + (cum - prev_cum) * max(0.0, min(1.0, frac))
        prev_bound, prev_cum = bound, cum
    return float(prev_cum)


class SLOEvaluator:
    """Scores every stored SLOPolicy against the windowed latency families
    and republishes the training_slo_* gauges. One instance per control
    plane (the fleet plane ticks it); incident state is in-memory — a
    restart re-fires an ongoing incident's event, which is the right bias
    (an unnoticed page beats a silently swallowed one)."""

    def __init__(self, api, now_fn: Callable[[], float],
                 enable_events: bool = True,
                 queue_shares_interval: float = 60.0):
        self.api = api
        self.now = now_fn
        self.enable_events = enable_events
        # The per-queue attribution aggregate is a slow-moving advisory
        # signal and the priciest part of the tick (it sweeps live-job
        # timelines); refresh it at most this often on the evaluation
        # clock, serving the cached copy in between.
        self.queue_shares_interval = queue_shares_interval
        self._shares: Optional[Dict[str, Dict[str, float]]] = None
        self._shares_at: Optional[float] = None
        # (policy, objective) keys currently burning — the once-per-incident
        # edge detector for SLOBurnRate events.
        self._burning: set = set()
        # Gauge label tuples published last pass, per gauge — stale tuples
        # (deleted policy/objective) are zeroed, FleetCollector-style, so a
        # removed SLO doesn't freeze its last value on the scrape surface.
        self._published: Dict[Any, set] = {}
        # Per-job attribution memo for the queue-shares pass (see
        # aggregate_queue_shares): finished jobs' decompositions are
        # now-independent, so repeat evaluations reuse them.
        self._attr_cache: Dict[Any, Any] = {}

    # -- scoring -----------------------------------------------------------

    def _matching_views(self, obj: SLOObjective, window_s: float,
                        now: float) -> Tuple[List[Tuple[float, int]], int]:
        family = SLO_METRICS[obj.metric]
        views = []
        for (queue, kind), child in family.children():
            if obj.queue and queue != obj.queue:
                continue
            if obj.kind and kind != obj.kind:
                continue
            views.append(child.cumulative_buckets(window_s, now))
        merged = _merge_views(views)
        total = merged[-1][1] if merged else 0
        return merged, total

    def _score(self, obj: SLOObjective, now: float) -> Dict[str, Any]:
        budget = 1.0 - obj.target
        row: Dict[str, Any] = {
            "objective": obj.name,
            "metric": obj.metric,
            "queue": obj.queue or "*",
            "kind": obj.kind or "*",
            "threshold_seconds": obj.threshold_seconds,
            "target": obj.target,
        }
        burns = {}
        for window_name, window_s in (
            ("fast", obj.fast_window_seconds),
            ("slow", obj.slow_window_seconds),
        ):
            view, total = self._matching_views(obj, window_s, now)
            good = _good_count(view, obj.threshold_seconds) if total else 0.0
            bad_fraction = 1.0 - (good / total) if total else 0.0
            burns[window_name] = bad_fraction / budget
            row[f"samples_{window_name}"] = total
            if window_name == "slow":
                row["attainment"] = (good / total) if total else 1.0
        row["burn_fast"] = burns["fast"]
        row["burn_slow"] = burns["slow"]
        row["budget_remaining"] = max(0.0, min(1.0, 1.0 - burns["slow"]))
        row["burning"] = bool(
            row["samples_fast"]
            and row["samples_slow"]
            and burns["fast"] >= obj.burn_threshold
            and burns["slow"] >= obj.burn_threshold
        )
        return row

    # -- publication -------------------------------------------------------

    def _set_gauges(self, policy_name: str, row: Dict[str, Any]) -> None:
        key3 = (policy_name, row["objective"], row["queue"])
        metrics.slo_attainment_ratio.set(*key3, value=row["attainment"])
        metrics.slo_budget_remaining.set(*key3, value=row["budget_remaining"])
        metrics.slo_burn_rate.set(*key3, "fast", value=row["burn_fast"])
        metrics.slo_burn_rate.set(*key3, "slow", value=row["burn_slow"])

    def _zero_stale(self, fresh: Dict[Any, set]) -> None:
        for gauge, old_keys in self._published.items():
            for key in old_keys - fresh.get(gauge, set()):
                gauge.set(*key, value=0.0)
        self._published = fresh

    def _fire_incident(self, policy: SLOPolicy, row: Dict[str, Any],
                       now: float) -> None:
        from training_operator_tpu.cluster.objects import Event

        self.api.record_event(Event(
            object_kind=SLOPolicy.KIND,
            object_name=policy.name,
            namespace="",
            event_type="Warning",
            reason="SLOBurnRate",
            message=(
                f"objective {row['objective']!r} "
                f"(queue={row['queue']}, kind={row['kind']}) burning at "
                f"{row['burn_fast']:.2f}x/{row['burn_slow']:.2f}x over "
                f"{int(row['windows'][0])}s/{int(row['windows'][1])}s windows "
                f"(target {row['target']:.4g}, "
                f"threshold {row['threshold_seconds']:.4g}s)"
            ),
            timestamp=now,
        ))

    # -- the tick ----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Score every policy; returns the `slo` fleet section."""
        at = self.now() if now is None else now
        # Rotate idle windows forward so a quiet queue's old breaches age
        # out on the evaluation clock, not on its next observation.
        for family in SLO_METRICS.values():
            for _, child in family.children():
                child.advance(at)

        policies = sorted(
            self.api.list(SLOPolicy.KIND), key=lambda p: p.metadata.name
        )
        rows: List[Dict[str, Any]] = []
        fresh: Dict[Any, set] = {}
        burning_now: set = set()
        for policy in policies:
            for obj in policy.objectives:
                row = self._score(obj, at)
                row["policy"] = policy.name
                row["windows"] = (obj.fast_window_seconds,
                                  obj.slow_window_seconds)
                self._set_gauges(policy.name, row)
                key3 = (policy.name, row["objective"], row["queue"])
                fresh.setdefault(metrics.slo_attainment_ratio, set()).add(key3)
                fresh.setdefault(metrics.slo_budget_remaining, set()).add(key3)
                ring = fresh.setdefault(metrics.slo_burn_rate, set())
                ring.add(key3 + ("fast",))
                ring.add(key3 + ("slow",))
                incident_key = (policy.name, row["objective"], row["queue"])
                if row["burning"]:
                    burning_now.add(incident_key)
                    if (incident_key not in self._burning
                            and self.enable_events):
                        self._fire_incident(policy, row, at)
                rows.append(row)
        self._zero_stale(fresh)
        self._burning = burning_now

        section: Dict[str, Any] = {
            "t": at,
            "policies": len(policies),
            "objectives": [
                {k: v for k, v in row.items() if k != "windows"}
                for row in rows
            ],
            "incidents": len(burning_now),
        }
        # Per-queue aggregate attribution shares — the autoscaler's "why is
        # this queue slow" signal, riding the same section.
        if (self._shares_at is None or at < self._shares_at
                or at - self._shares_at >= self.queue_shares_interval):
            try:
                from training_operator_tpu.observe.attribution import (
                    aggregate_queue_shares,
                )

                self._shares = aggregate_queue_shares(
                    self.api, at, cache=self._attr_cache)
            except Exception:
                # Attribution is advisory; a malformed timeline must not
                # take down the burn-rate surface with it.
                pass
            self._shares_at = at
        if self._shares:
            section["queues"] = self._shares
        return section


def render_slo(section: Dict[str, Any]) -> str:
    """Human form of the `slo` section for `top` — one line per objective,
    worst burn first."""
    rows = sorted(
        section.get("objectives", []),
        key=lambda r: -float(r.get("burn_slow", 0.0)),
    )
    lines = [
        f"SLO: {section.get('policies', 0)} policies, "
        f"{len(rows)} objectives, {section.get('incidents', 0)} burning"
    ]
    for r in rows:
        flag = " BURNING" if r.get("burning") else ""
        lines.append(
            f"  {r['policy']}/{r['objective']} "
            f"[{r['metric']} queue={r['queue']} kind={r['kind']} "
            f"<= {r['threshold_seconds']:g}s @ {r['target']:.4g}] "
            f"attain {r['attainment']:.4f}  budget {r['budget_remaining']:.3f}  "
            f"burn {r['burn_fast']:.2f}x/{r['burn_slow']:.2f}x "
            f"(n={r['samples_slow']}){flag}"
        )
    queues = section.get("queues") or {}
    for queue, shares in sorted(queues.items()):
        top = sorted(shares.items(), key=lambda kv: -kv[1])[:3]
        if top:
            mix = ", ".join(f"{cause} {share:.0%}" for cause, share in top)
            lines.append(f"  queue {queue}: waiting on {mix}")
    return "\n".join(lines)
