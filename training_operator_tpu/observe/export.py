"""Chrome-trace (catapult) exporter for job timelines.

`export_chrome_trace` turns timelines into the Trace Event Format JSON
that chrome://tracing and Perfetto load directly, so a bench run can dump
the full burst's phase structure for offline flame views:

    from training_operator_tpu import observe
    observe.export_chrome_trace(api.timelines, "/tmp/burst-trace.json")

Each job becomes one "process" row (pid + process_name metadata); spans
become complete ("X") duration events. Cluster-clock seconds map to trace
microseconds; spans whose cluster interval is instantaneous but which
carry a real `wall` measurement (solver time on a virtual clock) use the
wall duration, so the flame widths stay truthful.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Union

from training_operator_tpu.observe.timeline import JobTimeline, TimelineStore


def _as_timeline_dicts(source) -> List[Dict[str, Any]]:
    if isinstance(source, TimelineStore):
        return [tl.to_dict() for tl in source.timelines()]
    if isinstance(source, JobTimeline):
        return [source.to_dict()]
    if isinstance(source, dict):
        return [source]
    out = []
    for item in source:
        if isinstance(item, JobTimeline):
            out.append(item.to_dict())
        elif item:  # plain timeline dict (wire shape)
            out.append(item)
    return out


def export_chrome_trace(
    source: Union[TimelineStore, JobTimeline, Dict[str, Any], list],
    path: Optional[str] = None,
) -> Dict[str, Any]:
    """Build (and optionally write) a Trace Event Format document from a
    TimelineStore, JobTimeline(s), or wire-shaped timeline dict(s)."""
    events: List[Dict[str, Any]] = []
    for pid, tl in enumerate(_as_timeline_dicts(source), start=1):
        job = f"{tl.get('namespace', '')}/{tl.get('name', '')}"
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": job},
        })
        for span in tl.get("spans", []):
            start = float(span.get("start", 0.0))
            end = float(span.get("end", 0.0))
            wall = float(span.get("wall", 0.0))
            dur = wall if wall > 0.0 else max(0.0, end - start)
            events.append({
                "ph": "X",
                "name": span.get("name", ""),
                "pid": pid,
                "tid": 0,
                "ts": round(start * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "args": dict(span.get("attrs", {})),
            })
        for mark, t in sorted(tl.get("marks", {}).items(), key=lambda kv: kv[1]):
            events.append({
                "ph": "i", "s": "p", "name": mark, "pid": pid, "tid": 0,
                "ts": round(float(t) * 1e6, 3), "args": {},
            })
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc


def export_chrome_trace_merged(
    sources: Dict[str, Union[TimelineStore, JobTimeline, Dict[str, Any], list]],
    path: Optional[str] = None,
) -> Dict[str, Any]:
    """Merge timelines from MANY processes — store shards, operator-shard
    replicas — into one Trace Event document on one clock.

    `sources` maps a process label ("shard-0", "replica-b", ...) to any
    source `export_chrome_trace` accepts (the sharded router's
    `get_timelines()` fan-out hands back exactly this shape). Each source
    becomes one trace PROCESS (pid + process_name metadata); each job
    within it becomes one named THREAD (tid + thread_name), so a job whose
    spans landed on several shards/replicas reads as parallel rows under
    distinct processes, aligned on the shared cluster clock — timestamps
    are already comparable, no skew correction is applied or needed.
    """
    events: List[Dict[str, Any]] = []
    for pid, label in enumerate(sorted(sources), start=1):
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
        for tid, tl in enumerate(_as_timeline_dicts(sources[label]), start=1):
            job = f"{tl.get('namespace', '')}/{tl.get('name', '')}"
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": job},
            })
            for span in tl.get("spans", []):
                start = float(span.get("start", 0.0))
                end = float(span.get("end", 0.0))
                wall = float(span.get("wall", 0.0))
                dur = wall if wall > 0.0 else max(0.0, end - start)
                events.append({
                    "ph": "X",
                    "name": span.get("name", ""),
                    "pid": pid,
                    "tid": tid,
                    "ts": round(start * 1e6, 3),
                    "dur": round(dur * 1e6, 3),
                    "args": dict(span.get("attrs", {})),
                })
            for mark, t in sorted(
                tl.get("marks", {}).items(), key=lambda kv: kv[1]
            ):
                events.append({
                    "ph": "i", "s": "p", "name": mark, "pid": pid, "tid": tid,
                    "ts": round(float(t) * 1e6, 3), "args": {},
                })
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f)
    return doc
