"""Fleet snapshot collector + `top` renderer: live utilization in one view.

Answers the half of "is the fleet healthy right now?" that isn't a rule
(observe/invariants.py is the other half): per-node and per-slice chip
utilization, gang/queue depths, job counts by kind and state, store object
counts, journal bytes, watch-session and resume-ring occupancy. One
`collect_fleet` walk produces the wire payload `GET /fleet` serves (byte-
cached by store version, so polling it is cheap), the gauges the
`FleetCollector` republishes as `training_fleet_*`, and the table
`python -m training_operator_tpu top` renders — three surfaces, one
collector, so they cannot disagree.

ROADMAP open item 5's autoscaler is the intended machine consumer: the
fleet dict carries exactly the live utilization/queue signals an elasticity
loop needs, already shaped for the wire.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from training_operator_tpu.observe.invariants import FleetSources
from training_operator_tpu.utils import metrics

# Per-node detail rows are capped: at 10k nodes the detail table would be
# the payload; `top` shows slices, aggregates stay exact.
MAX_NODE_ROWS = 64


def job_state(job: Any) -> str:
    """Uniform lifecycle state for any job-shaped object — v1 jobs (from
    their condition list) and v2 TrainJobs: pending | running | succeeded
    | failed. THE classification the fleet gauges, /fleet, and `top` share."""
    from training_operator_tpu.api import common as capi

    if hasattr(job, "replica_specs"):  # v1 job
        if capi.is_succeeded(job.status):
            return "succeeded"
        if capi.has_condition(job.status, capi.JobConditionType.FAILED):
            return "failed"
        if capi.is_running(job.status):
            return "running"
        return "pending"
    # v2 TrainJob: Complete/Failed are terminal; jobs_status says whether
    # the workload has materialized (created -> it is driving pods).
    from training_operator_tpu.runtime.api import TrainJobConditionType

    complete = job.condition(TrainJobConditionType.COMPLETE)
    if complete is not None and complete.status:
        return "succeeded"
    failed = job.condition(TrainJobConditionType.FAILED)
    if failed is not None and failed.status:
        return "failed"
    return "running" if job.status.jobs_status else "pending"


def collect_fleet(api, now: float,
                  sources: Optional[FleetSources] = None) -> Dict[str, Any]:
    """One point-in-time fleet snapshot as a JSON-shaped dict. Reads the
    store through `list_refs` (frozen references, no clones) — a collection
    pass over a 10k-node store is one walk, not one deep copy per object."""
    from training_operator_tpu.api.jobs import JOB_KINDS
    from training_operator_tpu.cluster.inventory import TPU_RESOURCE
    from training_operator_tpu.cluster.objects import node_ready

    sources = sources or FleetSources()
    nodes = list(api.list_refs("Node"))
    pods = list(api.list_refs("Pod"))
    groups = list(api.list_refs("PodGroup"))

    # Per-node chip/cpu usage from bound non-terminal pods.
    used_by_node: Dict[str, Dict[str, float]] = {}
    for pod in pods:
        if not pod.node_name or pod.is_terminal():
            continue
        bucket = used_by_node.setdefault(pod.node_name, {})
        for k, v in pod.resources().items():
            bucket[k] = bucket.get(k, 0.0) + v

    ready = notready = cordoned = 0
    chips_total = chips_used = 0.0
    free_tpu_hosts = 0
    slices: Dict[str, Dict[str, Any]] = {}
    node_rows: List[Dict[str, Any]] = []
    for node in sorted(nodes, key=lambda n: n.metadata.name):
        is_ready = node_ready(node)
        if is_ready:
            ready += 1
        else:
            notready += 1
        if node.unschedulable:
            cordoned += 1
        cap_chips = node.capacity.get(TPU_RESOURCE, 0.0)
        used = used_by_node.get(node.metadata.name, {})
        used_chips = min(cap_chips, used.get(TPU_RESOURCE, 0.0))
        chips_total += cap_chips
        chips_used += used_chips
        acc = node.accelerator
        if acc.kind == "tpu" and acc.tpu_slice:
            sl = slices.setdefault(acc.tpu_slice, {
                "slice": acc.tpu_slice,
                "topology": acc.slice_topology,
                "hosts": 0,
                "free_hosts": 0,
                "ready_hosts": 0,
                "chips": 0.0,
                "chips_used": 0.0,
            })
            sl["hosts"] += 1
            sl["chips"] += cap_chips
            sl["chips_used"] += used_chips
            if is_ready:
                sl["ready_hosts"] += 1
            if used_chips == 0.0 and is_ready and not node.unschedulable:
                sl["free_hosts"] += 1
                free_tpu_hosts += 1
        if len(node_rows) < MAX_NODE_ROWS:
            node_rows.append({
                "node": node.metadata.name,
                "ready": is_ready,
                "cordoned": node.unschedulable,
                "slice": acc.tpu_slice,
                "chips": cap_chips,
                "chips_used": used_chips,
                "cpu": node.capacity.get("cpu", 0.0),
                "cpu_used": used.get("cpu", 0.0),
            })

    podgroups: Dict[str, int] = {}
    for pg in groups:
        phase = getattr(pg.phase, "value", str(pg.phase))
        podgroups[phase] = podgroups.get(phase, 0) + 1

    # Tenancy queues: quota vs admitted/pending/borrowed, from the SAME
    # accounting the arbiter admits against (tenancy/arbiter.py) so the
    # `queues` CLI, the gauges, and admission can never disagree.
    queue_rows: List[Dict[str, Any]] = []
    cluster_queues = list(api.list_refs("ClusterQueue"))
    if cluster_queues:
        from training_operator_tpu.tenancy.arbiter import (
            admitted_usage,
            pending_usage,
        )

        by_name = {q.metadata.name: q for q in cluster_queues}
        admitted = admitted_usage(groups, by_name)
        pending = pending_usage(groups, by_name)
        for name in sorted(by_name):
            q = by_name[name]
            held = admitted.get(name, {})
            chips_held = held.get(TPU_RESOURCE, 0.0)
            quota_chips = q.quota.get(TPU_RESOURCE, 0.0)
            queue_rows.append({
                "queue": name,
                "weight": q.weight,
                "quota": dict(q.quota),
                "borrowing_limit": dict(q.borrowing_limit),
                "admitted": dict(held),
                "pending": dict(pending.get(name, {})),
                "admitted_chips": chips_held,
                "pending_chips": pending.get(name, {}).get(TPU_RESOURCE, 0.0),
                "borrowed_chips": max(0.0, chips_held - quota_chips),
                "quota_chips": quota_chips,
            })

    jobs: Dict[str, Dict[str, int]] = {}
    for kind in ("TrainJob", *JOB_KINDS):
        counts: Dict[str, int] = {}
        for job in api.list_refs(kind):
            state = job_state(job)
            counts[state] = counts.get(state, 0) + 1
        if counts:
            jobs[kind] = counts

    store: Dict[str, Any] = {}
    if sources.journal_bytes is not None:
        store["journal_bytes"] = int(sources.journal_bytes())
    if sources.journal_bound is not None:
        store["journal_bound"] = int(sources.journal_bound())
    if sources.watch_sessions is not None:
        store["watch_sessions"] = int(sources.watch_sessions())
    if sources.resume_ring is not None:
        rings = sources.resume_ring()
        store["resume_ring_events"] = sum(occ for occ, _ in rings.values())
        store["resume_ring_size"] = max(
            (size for _, size in rings.values()), default=0
        )
    expectations = 0
    if sources.expectations is not None:
        expectations = len(sources.expectations())
    # Replication lag (standby hosts): the INV008 feed verbatim, so `top`
    # against a standby shows how warm it actually is.
    replication = None
    if sources.replication_lag is not None:
        replication = dict(sources.replication_lag())

    # Sharded operator ownership: the leases are the durable record (any
    # deployment shape can render who owns what from the store alone); the
    # live claims feed — present in-process — adds what leases can't say
    # (a replica still claiming a shard it lost). One section serves
    # GET /fleet, the gauges, `top`, and the INV010 evidence trail.
    shard_plane = None
    shard_leases = []
    members = []
    from training_operator_tpu.controllers.leader import (
        MEMBER_LEASE_PREFIX,
        SHARD_LEASE_PREFIX,
        SHARD_NAMESPACE,
    )

    for lease in api.list_refs("Lease", SHARD_NAMESPACE):
        lname = lease.metadata.name
        if lname.startswith(SHARD_LEASE_PREFIX):
            shard_leases.append({
                "shard": int(lname[len(SHARD_LEASE_PREFIX):]),
                "holder": lease.holder,
                "expired": lease.expired(now),
                "age": round(max(0.0, now - lease.renew_time), 1),
            })
        elif lname.startswith(MEMBER_LEASE_PREFIX):
            if lease.holder and not lease.expired(now):
                members.append(lease.holder)
    if shard_leases or members or sources.shards is not None:
        owners: Dict[str, int] = {}
        for row in shard_leases:
            if row["holder"] and not row["expired"]:
                owners[row["holder"]] = owners.get(row["holder"], 0) + 1
        shard_plane = {
            "num_shards": len(shard_leases),
            "leases": sorted(shard_leases, key=lambda r: r["shard"]),
            "members": sorted(set(members)),
            "owners": owners,
            "unowned": sum(
                1 for r in shard_leases
                if not r["holder"] or r["expired"]
            ),
        }
        if sources.shards is not None:
            info = sources.shards()
            shard_plane["num_shards"] = max(
                shard_plane["num_shards"], int(info.get("num_shards", 0))
            )
            shard_plane["claims"] = {
                ident: list(shards)
                for ident, shards in sorted(
                    (info.get("claims") or {}).items()
                )
            }

    # Sharded write plane: the StoreShardSet's ownership report verbatim
    # (per-shard object counts + duplicate/misrouted evidence) — the same
    # feed INV011 audits, so `top`, GET /fleet, and the auditor cannot
    # disagree about which shard owns what.
    store_shard_plane = None
    if sources.store_shards is not None:
        store_shard_plane = dict(sources.store_shards())

    # SLO plane: one evaluator pass per collect — burn-rate scoring and the
    # training_slo_* gauge republish happen inside evaluate(), the returned
    # section rides the snapshot for GET /fleet and `top`.
    slo_section = None
    if sources.slo is not None:
        slo_section = dict(sources.slo())

    # Gang-solver cycle stats (the training_solver_* counter families +
    # the solve-wall histogram), so `top` and the /fleet consumers see the
    # O(changed) plane without scraping /metrics separately.
    solve_hist = metrics.scheduler_solve_seconds
    solver = {
        "cycles": int(metrics.solver_cycles.total()),
        "incremental_cycles": int(metrics.solver_incremental_cycles.total()),
        "groups_resolved": int(metrics.solver_groups_resolved.total()),
        "snapshot_rebuilds": int(metrics.solver_snapshot_rebuilds.total()),
        "wall_total_s": round(solve_hist.sum, 4),
        "wall_mean_s": round(solve_hist.mean(), 6),
    }

    return {
        "t": now,
        "nodes": {
            "total": len(nodes), "ready": ready, "notready": notready,
            "cordoned": cordoned,
        },
        "node_rows": node_rows,
        "nodes_truncated": len(nodes) > len(node_rows),
        "slices": [slices[k] for k in sorted(slices)],
        "chips": {"total": chips_total, "used": chips_used},
        "free_tpu_hosts": free_tpu_hosts,
        "whole_free_slices": sum(
            1 for s in slices.values() if s["free_hosts"] == s["hosts"]
        ),
        "podgroups": podgroups,
        "solver": solver,
        "queues": queue_rows,
        "queue": {
            "pending_gangs": podgroups.get("Pending", 0)
            + podgroups.get("Unschedulable", 0),
            "workqueue_depth": metrics.workqueue_depth.value(),
            "unfulfilled_expectations": expectations,
        },
        "jobs": jobs,
        "objects": api.object_counts(),
        "store": store,
        **({"replication": replication} if replication is not None else {}),
        **({"shards": shard_plane} if shard_plane is not None else {}),
        **({"store_shards": store_shard_plane}
           if store_shard_plane is not None else {}),
        **({"slo": slo_section} if slo_section is not None else {}),
    }


class FleetCollector:
    """Periodic republisher: one `collect_fleet` walk per `interval` on the
    cluster clock, exported as `training_fleet_*` gauges through the
    process registry (so `/metrics` + `/metrics.txt` carry the fleet view
    without a /fleet poll). Holds the latest snapshot for local readers.

    `auditor`: an (unattached) InvariantAuditor to drive from the SAME
    timer — one fleet-plane tick per interval instead of two drifting
    timers each walking the store."""

    def __init__(self, cluster, sources: Optional[FleetSources] = None,
                 interval: float = 30.0, auditor=None):
        self.cluster = cluster
        self.sources = sources or FleetSources()
        self.interval = interval
        self.auditor = auditor
        self.last: Optional[Dict[str, Any]] = None
        # Label tuples set last round, per dynamic-label family: a bucket
        # that empties (every Pending gang admitted, a kind GC'd from the
        # store) must be zeroed, not left at its last value — a stale
        # phantom gauge would tell the autoscaler there is pending work
        # forever, and /metrics would disagree with /fleet.
        self._published: Dict[str, set] = {}
        self._armed = True
        cluster.schedule_after(interval, self._tick)

    def stop(self) -> None:
        self._armed = False

    def _tick(self) -> None:
        if not self._armed:
            return
        try:
            self.collect()
        finally:
            if self._armed:
                self.cluster.schedule_after(self.interval, self._tick)

    def collect(self) -> Dict[str, Any]:
        if self.auditor is not None:
            # Audit first: the violations gauge and audit seq are then
            # coherent with the snapshot this same tick publishes.
            self.auditor.audit()
        fleet = collect_fleet(
            self.cluster.api, self.cluster.clock.now(), self.sources
        )
        self.publish(fleet)
        self.last = fleet
        return fleet

    def _set_family(self, gauge, values: Dict[tuple, float]) -> None:
        """Publish one dynamic-label gauge family, zeroing every label
        tuple that was set on a previous round but is absent now."""
        stale = self._published.get(gauge.name, set()) - set(values)
        for labels in stale:
            gauge.set(*labels, value=0.0)
        for labels, v in values.items():
            gauge.set(*labels, value=v)
        self._published[gauge.name] = set(values)

    def publish(self, fleet: Dict[str, Any]) -> None:
        n = fleet["nodes"]
        metrics.fleet_nodes.set("ready", value=float(n["ready"]))
        metrics.fleet_nodes.set("notready", value=float(n["notready"]))
        metrics.fleet_nodes.set("cordoned", value=float(n["cordoned"]))
        metrics.fleet_chips_total.set(value=float(fleet["chips"]["total"]))
        metrics.fleet_chips_used.set(value=float(fleet["chips"]["used"]))
        metrics.fleet_free_tpu_hosts.set(value=float(fleet["free_tpu_hosts"]))
        metrics.fleet_whole_free_slices.set(
            value=float(fleet["whole_free_slices"])
        )
        self._set_family(metrics.fleet_podgroups, {
            (phase,): float(count)
            for phase, count in fleet["podgroups"].items()
        })
        self._set_family(metrics.fleet_jobs, {
            (kind, state): float(count)
            for kind, counts in fleet["jobs"].items()
            for state, count in counts.items()
        })
        self._set_family(metrics.fleet_objects, {
            (kind,): float(count)
            for kind, count in fleet["objects"].items()
        })
        queues = fleet.get("queues") or []
        self._set_family(metrics.queue_admitted_chips, {
            (row["queue"],): float(row["admitted_chips"]) for row in queues
        })
        self._set_family(metrics.queue_pending_chips, {
            (row["queue"],): float(row["pending_chips"]) for row in queues
        })
        self._set_family(metrics.queue_borrowed_chips, {
            (row["queue"],): float(row["borrowed_chips"]) for row in queues
        })
        store = fleet["store"]
        if "journal_bytes" in store:
            metrics.fleet_journal_bytes.set(
                value=float(store["journal_bytes"])
            )
        if "watch_sessions" in store:
            metrics.fleet_watch_sessions.set(
                value=float(store["watch_sessions"])
            )
        if "resume_ring_events" in store:
            metrics.fleet_resume_ring_events.set(
                value=float(store["resume_ring_events"])
            )


# ---------------------------------------------------------------------------
# `top` renderer
# ---------------------------------------------------------------------------


def _bar(used: float, total: float, width: int = 20) -> str:
    if total <= 0:
        return "-" * width
    filled = int(round(width * min(1.0, used / total)))
    return "#" * filled + "." * (width - filled)


def render_queues(queue_rows: List[Dict[str, Any]]) -> str:
    """Table of one fleet snapshot's tenancy queues (the `queues` CLI and
    `top`'s CLUSTERQUEUE section share this renderer)."""
    if not queue_rows:
        return "clusterqueues: none"
    lines = [
        f"  {'CLUSTERQUEUE':<16} {'WEIGHT':>6} {'QUOTA':>8} {'ADMITTED':>9} "
        f"{'BORROWED':>9} {'PENDING':>8} UTIL"
    ]
    for row in queue_rows:
        lines.append(
            f"  {row['queue']:<16} {row['weight']:>6.1f} "
            f"{row['quota_chips']:>8.0f} {row['admitted_chips']:>9.0f} "
            f"{row['borrowed_chips']:>9.0f} {row['pending_chips']:>8.0f} "
            f"{_bar(row['admitted_chips'], row['quota_chips'])}"
        )
    return "\n".join(lines)


def render_top(fleet: Dict[str, Any]) -> str:
    """The kubectl-top analogue for one fleet snapshot: slice/node chip
    utilization, gang/queue depths, job counts, live violations."""
    lines: List[str] = []
    n = fleet["nodes"]
    chips = fleet["chips"]
    pct = 100.0 * chips["used"] / chips["total"] if chips["total"] else 0.0
    lines.append(
        f"fleet @ t={fleet['t']:.1f}  nodes: {n['total']} "
        f"({n['ready']} ready, {n['notready']} notready, "
        f"{n['cordoned']} cordoned)  chips: {chips['used']:.0f}/"
        f"{chips['total']:.0f} ({pct:.1f}%)"
    )

    if fleet["slices"]:
        lines.append("")
        lines.append(f"  {'SLICE':<16} {'TOPO':<8} {'HOSTS':>5} {'FREE':>5} "
                     f"{'CHIPS':>12} UTIL")
        for sl in fleet["slices"]:
            lines.append(
                f"  {sl['slice']:<16} {sl['topology']:<8} {sl['hosts']:>5} "
                f"{sl['free_hosts']:>5} "
                f"{sl['chips_used']:>5.0f}/{sl['chips']:<6.0f} "
                f"{_bar(sl['chips_used'], sl['chips'])}"
            )
    elif fleet["node_rows"]:
        lines.append("")
        lines.append(f"  {'NODE':<24} {'READY':<6} {'CPU':>12} {'CHIPS':>10}")
        for row in fleet["node_rows"]:
            lines.append(
                f"  {row['node']:<24} {str(row['ready']):<6} "
                f"{row['cpu_used']:>5.1f}/{row['cpu']:<6.1f} "
                f"{row['chips_used']:>4.0f}/{row['chips']:<5.0f}"
            )
        if fleet.get("nodes_truncated"):
            lines.append(f"  ... ({n['total']} nodes total)")

    q = fleet["queue"]
    pg = fleet["podgroups"]
    lines.append("")
    lines.append(
        "queues:  pending gangs "
        f"{q['pending_gangs']}  inqueue {pg.get('Inqueue', 0)}  "
        f"running {pg.get('Running', 0)}  workqueue depth "
        f"{q['workqueue_depth']:.0f}  expectations "
        f"{q['unfulfilled_expectations']}"
    )

    solver = fleet.get("solver")
    if solver and solver.get("cycles"):
        inc = solver.get("incremental_cycles", 0)
        cycles = solver["cycles"]
        lines.append(
            "solver:  "
            f"cycles {cycles} ({inc} incremental, "
            f"{100.0 * inc / cycles:.0f}%)  groups solved "
            f"{solver.get('groups_resolved', 0)}  wall mean "
            f"{1000.0 * solver.get('wall_mean_s', 0.0):.2f}ms  "
            f"snapshot rebuilds {solver.get('snapshot_rebuilds', 0)}"
        )

    if fleet.get("queues"):
        lines.append("")
        lines.append(render_queues(fleet["queues"]))

    if fleet["jobs"]:
        lines.append("")
        lines.append(f"  {'KIND':<16} {'PENDING':>8} {'RUNNING':>8} "
                     f"{'SUCCEEDED':>10} {'FAILED':>7}")
        for kind in sorted(fleet["jobs"]):
            c = fleet["jobs"][kind]
            lines.append(
                f"  {kind:<16} {c.get('pending', 0):>8} "
                f"{c.get('running', 0):>8} {c.get('succeeded', 0):>10} "
                f"{c.get('failed', 0):>7}"
            )

    store = fleet.get("store") or {}
    if store:
        parts = []
        if "journal_bytes" in store:
            parts.append(f"journal {store['journal_bytes']}B")
        if "watch_sessions" in store:
            parts.append(f"watch sessions {store['watch_sessions']}")
        if "resume_ring_events" in store:
            parts.append(f"resume ring {store['resume_ring_events']} events")
        if parts:
            lines.append("")
            lines.append("store:   " + "  ".join(parts))

    shards = fleet.get("shards")
    if shards and shards.get("num_shards"):
        owners = shards.get("owners") or {}
        owner_str = "  ".join(
            f"{ident}={count}" for ident, count in sorted(owners.items())
        ) or "none"
        lines.append("")
        lines.append(
            f"shards:  {shards['num_shards']} total  "
            f"unowned {shards.get('unowned', 0)}  "
            f"members {len(shards.get('members') or [])}  "
            f"owned: {owner_str}"
        )

    store_shards = fleet.get("store_shards")
    if store_shards and store_shards.get("num_shards"):
        counts = store_shards.get("counts") or {}
        count_str = "  ".join(
            f"s{idx}={counts[idx]}" for idx in sorted(counts)
        ) or "none"
        lines.append("")
        lines.append(
            f"store shards: {store_shards['num_shards']} "
            f"(meta={store_shards.get('meta_shard', 0)})  "
            f"objects: {count_str}  "
            f"dup {len(store_shards.get('duplicates') or [])}  "
            f"misrouted {len(store_shards.get('misrouted') or [])}"
        )

    repl = fleet.get("replication")
    if repl:
        lines.append("")
        lines.append(
            f"replication: role={repl.get('role')}  "
            f"lag {repl.get('records', 0)} records / "
            f"{repl.get('seconds', 0.0):.1f}s  "
            f"connected={repl.get('connected')}  "
            f"applied={repl.get('applied', 0)}  "
            f"bootstraps={repl.get('bootstraps', 0)}"
        )

    slo = fleet.get("slo")
    if slo is not None:
        from training_operator_tpu.observe.slo import render_slo

        lines.append("")
        lines.append(render_slo(slo))

    violations = fleet.get("violations") or []
    lines.append("")
    if violations:
        lines.append(f"violations: {len(violations)} ACTIVE")
        for v in violations:
            where = f"{v['namespace']}/{v['name']}" if v["namespace"] else v["name"]
            lines.append(
                f"  {v['rule']}  {v['object_kind']:<10} {where:<28} "
                f"{v['message']}"
            )
    else:
        lines.append("violations: none")
    return "\n".join(lines)
