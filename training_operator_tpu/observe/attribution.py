"""Per-job latency attribution: why was time-to-running spent where?

The SLO engine (observe/slo.py) says WHETHER jobs are slow; this module
says WHY. It joins three evidence streams that already exist — the job's
timeline spans (PR 4), its PodGroup's tenancy state, and the lifecycle
Events the scheduler/arbiter emit — and decomposes the job's
time-to-running into a REGISTERED cause taxonomy:

  quota_wait                gang held at the quota gate (QuotaExceeded)
  priority_wait             waiting its turn in the priority-ordered solve
  topology_fragmentation    no feasible placement found (Unschedulable)
  preemption_displacement   displaced by the fair-share arbiter (Preempted)
  node_loss_recovery        placement lost to a dead node (PlacementInvalidated
                            / node_evict) and re-solved
  control_plane_overhead    measured admission/queue/reconcile/solve/bind walls
  startup                   residual (container start, image pull analogue)

Causes must be drawn from this registry — codelint CL013 rejects free-text
cause strings, so dashboards and the item-3 autoscaler can rely on the ids
being a closed vocabulary.

The decomposition is an interval sweep, not a guess: each evidence item
opens an interval at its occurrence and closes at the next RECOVERY ANCHOR
(a GangAdmitted event, a bind, the Running instant); overlapping claims
resolve by fixed precedence (displacement > node loss > quota > topology >
priority); the uncovered residual splits into measured control-plane wall
time and startup. Rows therefore sum EXACTLY to the job's time-to-running
— the acceptance property tests/test_slo.py pins.

Works live ("why is my job not running yet": window = creation -> now) and
post-mortem (window = the recorded time_to_running span). Surfaced as
`TrainingClient.explain_job()`, `python -m training_operator_tpu explain
<ns>/<job>`, and `GET /explain/{ns}/{name}` — which the sharded router
serves from the job's owning store shard, where ALL its namespaced
evidence (timeline + Events + PodGroup) lives by construction.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Cause taxonomy (the closed vocabulary CL013 enforces)
# ---------------------------------------------------------------------------

CAUSES: "OrderedDict[str, str]" = OrderedDict()


def register_cause(cause_id: str, description: str) -> str:
    """Register one attribution cause; returns the id so call sites bind
    constants to registrations (free-text ids at use sites are a CL013
    finding)."""
    CAUSES[cause_id] = description
    return cause_id


CAUSE_QUOTA_WAIT = register_cause(
    "quota_wait",
    "held at the quota gate: queue usage + demand exceeded quota+borrowing",
)
CAUSE_PRIORITY_WAIT = register_cause(
    "priority_wait",
    "waiting for admission behind the priority-ordered solve queue",
)
CAUSE_TOPOLOGY_FRAGMENTATION = register_cause(
    "topology_fragmentation",
    "no feasible contiguous placement despite free capacity (Unschedulable)",
)
CAUSE_PREEMPTION_DISPLACEMENT = register_cause(
    "preemption_displacement",
    "displaced by the fair-share arbiter and re-queued (Preempted)",
)
CAUSE_NODE_LOSS_RECOVERY = register_cause(
    "node_loss_recovery",
    "placement invalidated by node loss / chaos and re-solved",
)
CAUSE_CONTROL_PLANE = register_cause(
    "control_plane_overhead",
    "measured admission + workqueue + reconcile + solve + bind wall time",
)
CAUSE_STARTUP = register_cause(
    "startup",
    "residual: container start and other unattributed ramp-up",
)

# Highest first — the pointwise winner where evidence intervals overlap
# (being displaced outranks the quota gate you also happen to be behind).
PRECEDENCE: Tuple[str, ...] = (
    CAUSE_PREEMPTION_DISPLACEMENT,
    CAUSE_NODE_LOSS_RECOVERY,
    CAUSE_QUOTA_WAIT,
    CAUSE_TOPOLOGY_FRAGMENTATION,
    CAUSE_PRIORITY_WAIT,
)

# Spans whose wall time is the control plane's own measured cost within the
# window (observe/describe.py PHASE_ORDER, minus the composite phases).
_CONTROL_PLANE_SPANS = (
    "admission", "queue_wait", "reconcile", "gang_solve", "bind",
)

# Event reasons -> the cause their occurrence evidences (scheduler/gang.py
# + tenancy/arbiter.py vocabulary).
_EVENT_CAUSES = {
    "Preempted": CAUSE_PREEMPTION_DISPLACEMENT,
    "PlacementInvalidated": CAUSE_NODE_LOSS_RECOVERY,
    "QuotaExceeded": CAUSE_QUOTA_WAIT,
    "Unschedulable": CAUSE_TOPOLOGY_FRAGMENTATION,
}

# Event reasons that close open evidence intervals: the gang is admitted
# again (or bound), so whatever it was waiting on has resolved.
_ANCHOR_REASONS = ("GangAdmitted",)


def _get(item: Any, key: str, default: Any = None) -> Any:
    """Field access over both dataclass Events and wire-decoded dicts."""
    if isinstance(item, dict):
        return item.get(key, default)
    return getattr(item, key, default)


def _event_instants(event: Any) -> List[float]:
    """Occurrence instants of one (possibly aggregated) Event: first and
    last timestamps. Intermediate occurrences of a count>2 aggregate are
    unrecoverable — the interval sweep tolerates that by construction."""
    last = float(_get(event, "timestamp", 0.0) or 0.0)
    first = float(_get(event, "first_timestamp", 0.0) or 0.0) or last
    return [first] if first == last else [first, last]


def attribute(
    timeline: Optional[Dict[str, Any]],
    events: Optional[List[Any]] = None,
    podgroup: Any = None,
    now: float = 0.0,
    created: Optional[float] = None,
) -> Dict[str, Any]:
    """Decompose one job's time-to-running into the registered causes.

    `timeline` is a JobTimeline dict (spans/marks); `events` the job's
    lifecycle Events; `podgroup` its PodGroup (or None). Pure function of
    its inputs — the deterministic core the wire route, the client, and the
    per-queue aggregates all share."""
    spans = list((timeline or {}).get("spans", ()))
    marks = dict((timeline or {}).get("marks", {}))
    events = events or []

    # -- the attribution window: creation -> first Running ----------------
    ttr_span = next(
        (s for s in spans if s.get("name") == "time_to_running"), None
    )
    if ttr_span is not None:
        t0, t1 = float(ttr_span["start"]), float(ttr_span["end"])
        running = True
    else:
        candidates = [float(created)] if created is not None else []
        if "created" in marks:
            candidates.append(float(marks["created"]))
        pg_created = getattr(
            getattr(podgroup, "metadata", None), "creation_time", None
        )
        if pg_created is not None:
            candidates.append(float(pg_created))
        candidates.extend(float(s["start"]) for s in spans if s.get("start"))
        t0 = min(candidates) if candidates else float(now)
        t1 = float(now)
        running = False
    total = max(0.0, t1 - t0)

    # -- recovery anchors: instants that close open evidence intervals ----
    anchors = [t1]
    for ev in events:
        if _get(ev, "reason") in _ANCHOR_REASONS:
            anchors.extend(_event_instants(ev))
    for s in spans:
        if s.get("name") in ("bind", "gang_solve"):
            anchors.append(float(s["end"]))
    if "running" in marks:
        anchors.append(float(marks["running"]))
    anchors = sorted(a for a in anchors if t0 <= a <= t1)

    def close_after(t: float) -> float:
        for a in anchors:
            if a > t:
                return a
        return t1

    # -- evidence intervals, clipped to the window -------------------------
    intervals: Dict[str, List[Tuple[float, float]]] = {}
    evidence: Dict[str, int] = {}

    def claim(cause: str, lo: float, hi: float) -> None:
        lo, hi = max(lo, t0), min(hi, t1)
        if hi > lo:
            intervals.setdefault(cause, []).append((lo, hi))
            evidence[cause] = evidence.get(cause, 0) + 1

    for ev in events:
        cause = _EVENT_CAUSES.get(_get(ev, "reason", ""))
        if cause is None:
            continue
        for te in _event_instants(ev):
            if te < t0 or te > t1:
                continue
            claim(cause, te, close_after(te))
    for s in spans:
        if s.get("name") == "node_evict":
            ts = float(s["start"])
            if t0 <= ts <= t1:
                claim(CAUSE_NODE_LOSS_RECOVERY, ts, close_after(ts))

    # Pre-admission wait: the stretch before the gang's FIRST admission,
    # claimable as priority_wait only when the job actually rode the
    # priority-ordered gang queue (it has a PodGroup) — lowest precedence,
    # so stronger evidence overlapping it wins pointwise.
    if podgroup is not None:
        first_admit = min(
            (a for a in anchors if a < t1), default=t1
        ) if anchors else t1
        claim(CAUSE_PRIORITY_WAIT, t0, first_admit)

    # -- precedence sweep: pointwise-highest cause wins --------------------
    bounds = sorted({t0, t1, *(
        b for ivs in intervals.values() for iv in ivs for b in iv
    )})
    seconds: Dict[str, float] = {}
    for lo, hi in zip(bounds, bounds[1:]):
        mid = (lo + hi) / 2.0
        for cause in PRECEDENCE:
            if any(a <= mid < b for a, b in intervals.get(cause, ())):
                seconds[cause] = seconds.get(cause, 0.0) + (hi - lo)
                break

    # -- residual: measured control-plane walls, then startup --------------
    covered = sum(seconds.values())
    residual = max(0.0, total - covered)
    cp_measured = sum(
        (s.get("wall") or 0.0)
        if (s.get("wall") or 0.0) > 0.0
        else max(0.0, float(s.get("end", 0.0)) - float(s.get("start", 0.0)))
        for s in spans
        if s.get("name") in _CONTROL_PLANE_SPANS
        and t0 <= float(s.get("end", 0.0)) <= t1
    )
    cp = min(residual, cp_measured)
    if cp > 0.0:
        seconds[CAUSE_CONTROL_PLANE] = cp
        evidence[CAUSE_CONTROL_PLANE] = sum(
            1 for s in spans if s.get("name") in _CONTROL_PLANE_SPANS
        )
    startup = residual - cp
    if startup > 0.0:
        seconds[CAUSE_STARTUP] = startup

    rows = [
        {
            "cause": cause,
            "seconds": secs,
            "share": (secs / total) if total > 0 else 0.0,
            "evidence": evidence.get(cause, 0),
            "description": CAUSES.get(cause, ""),
        }
        for cause, secs in sorted(seconds.items(), key=lambda kv: -kv[1])
    ]
    return {
        "namespace": (timeline or {}).get("namespace", ""),
        "name": (timeline or {}).get("name", ""),
        "running": running,
        "window": [t0, t1],
        "time_to_running_seconds": total,
        "causes": rows,
    }


# ---------------------------------------------------------------------------
# Evidence fetch + surfaces
# ---------------------------------------------------------------------------


def _fetch_timeline(api, namespace: str, name: str) -> Optional[Dict[str, Any]]:
    getter = getattr(api, "get_timeline", None)
    tl: Any = None
    if callable(getter):
        try:
            tl = getter(namespace, name)
        except Exception:
            tl = None
    if tl is None:
        store = getattr(api, "timelines", None)
        if store is not None and hasattr(store, "timeline"):
            tl = store.timeline(namespace, name)
    if tl is not None and hasattr(tl, "to_dict"):
        tl = tl.to_dict()
    return tl


def _job_events(api, namespace: str, name: str) -> List[Any]:
    try:
        evs = api.events(object_name=name)
    except Exception:
        return []
    return [
        ev for ev in evs
        if (_get(ev, "namespace", "") or "") in ("", namespace)
    ]


def _podgroup(api, namespace: str, name: str) -> Optional[Any]:
    """Read-only PodGroup evidence: the no-copy `get_ref` where the store
    offers it (attribution only reads attributes), `try_get` elsewhere."""
    ref_get = getattr(api, "get_ref", None)
    try:
        if callable(ref_get):
            return ref_get("PodGroup", namespace, name)
        return api.try_get("PodGroup", namespace, name)
    except Exception:
        return None


def _job_creation_time(api, namespace: str, name: str) -> Optional[float]:
    """The submitting job's creation_time, probing every job kind (the
    describe.find_job order). Prefers the store's no-copy `get_ref` read —
    explain needs one float, not a deep clone of the job — and falls back
    to `try_get` on surfaces without it (remote clients)."""
    try:
        from training_operator_tpu.api.jobs import JOB_KINDS
    except Exception:
        return None
    ref_get = getattr(api, "get_ref", None)
    for kind in ("TrainJob", *JOB_KINDS):
        try:
            job = (ref_get(kind, namespace, name) if callable(ref_get)
                   else api.try_get(kind, namespace, name))
        except Exception:
            job = None
        if job is not None:
            meta = getattr(job, "metadata", None)
            return getattr(meta, "creation_time", None)
    return None


def explain(api, namespace: str, name: str,
            now: Optional[float] = None) -> Dict[str, Any]:
    """Fetch one job's evidence (timeline + Events + PodGroup + creation
    time) and attribute its time-to-running. Works against the in-process
    APIServer, a RemoteAPIServer, or the sharded router — every surface
    exposes the same read verbs."""
    timeline = _fetch_timeline(api, namespace, name)
    events = _job_events(api, namespace, name)
    podgroup = _podgroup(api, namespace, name)
    created = _job_creation_time(api, namespace, name)
    if now is None:
        store = getattr(api, "timelines", None)
        if store is not None and hasattr(store, "now"):
            now = store.now()
        else:
            server_time = getattr(api, "server_time", None)
            if callable(server_time):
                try:
                    now = float(server_time())
                except Exception:
                    now = None
    if now is None:
        now = max(
            [float(s.get("end", 0.0)) for s in (timeline or {}).get("spans", ())]
            or [0.0]
        )
    report = attribute(
        timeline, events, podgroup=podgroup, now=now, created=created
    )
    report["namespace"] = namespace
    report["name"] = name
    return report


def render_explain(report: Dict[str, Any]) -> str:
    """kubectl-describe-flavored text form of one attribution report."""
    ns, name = report.get("namespace", ""), report.get("name", "")
    total = report.get("time_to_running_seconds", 0.0)
    state = (
        "reached Running" if report.get("running")
        else "NOT yet Running"
    )
    lines = [
        f"Job:             {ns}/{name}",
        f"State:           {state}",
        f"Time accounted:  {total:.3f}s "
        f"(window {report['window'][0]:.3f} -> {report['window'][1]:.3f})",
        "Causes:",
    ]
    rows = report.get("causes", [])
    if not rows:
        lines.append("  (nothing to attribute — zero-length window)")
    for row in rows:
        lines.append(
            f"  {row['cause']:<24} {row['seconds']:>10.3f}s "
            f"{row['share']:>7.1%}  {row['description']}"
        )
    return "\n".join(lines)


def aggregate_queue_shares(
    api, now: float, limit: int = 64,
    cache: Optional[Dict[Any, Any]] = None,
) -> Dict[str, Dict[str, float]]:
    """Per-queue attribution shares over the most recent retained
    timelines: {queue: {cause: share}}, shares summing to 1 per queue.
    Capped scan (newest `limit` jobs) — this rides the fleet tick, so it
    must stay O(recent jobs), not O(history).

    `cache` (caller-owned dict, e.g. the SLOEvaluator's) memoizes per-job
    cause totals: once a job holds a closed time_to_running span its
    attribution window is pinned — the report no longer depends on `now` —
    so a repeat evaluation with unchanged evidence (same span/event
    counts) reuses the cached decomposition instead of re-sweeping. The
    cache is pruned to the jobs seen this pass, so it stays <= limit."""
    store = getattr(api, "timelines", None)
    if store is None or not hasattr(store, "timelines"):
        return {}
    timelines = store.timelines()[-limit:]
    # One event pass, grouped by object name: this rides the fleet tick, so
    # it must stay O(events + jobs), not O(jobs x events) as per-job
    # `api.events(object_name=...)` scans would be.
    by_name: Dict[str, List[Any]] = {}
    try:
        for ev in api.events():
            by_name.setdefault(_get(ev, "object_name", ""), []).append(ev)
    except Exception:
        by_name = {}
    totals: Dict[str, Dict[str, float]] = {}
    seen: set = set()
    for tl in timelines:
        spans = getattr(tl, "sorted_spans", None)
        raw_spans = spans() if callable(spans) else (tl.get("spans") or [])
        ns = _get(tl, "namespace", "")
        name = _get(tl, "name", "")
        if not name:
            continue
        seen.add((ns, name))
        podgroup = _podgroup(api, ns, name)
        events = [
            ev for ev in by_name.get(name, ())
            if (_get(ev, "namespace", "") or "") in ("", ns)
        ]
        queue = getattr(podgroup, "queue", "") or "default"
        pinned = any(
            _get(s, "name", "") == "time_to_running" for s in raw_spans)
        key = (len(raw_spans), len(events), queue) if pinned else None
        hit = cache.get((ns, name)) if cache is not None else None
        if hit is not None and hit[0] == key and key is not None:
            causes = hit[1]
        else:
            d = tl.to_dict() if hasattr(tl, "to_dict") else tl
            report = attribute(d, events, podgroup=podgroup, now=now)
            causes = {
                row["cause"]: row["seconds"] for row in report["causes"]}
            if cache is not None and key is not None:
                cache[(ns, name)] = (key, causes)
        bucket = totals.setdefault(queue, {})
        for cause, seconds in causes.items():
            bucket[cause] = bucket.get(cause, 0.0) + seconds
    if cache is not None:
        for stale in [k for k in cache if k not in seen]:
            del cache[stale]
    shares: Dict[str, Dict[str, float]] = {}
    for queue, bucket in totals.items():
        denom = sum(bucket.values())
        if denom <= 0:
            continue
        shares[queue] = {
            cause: secs / denom for cause, secs in sorted(bucket.items())
        }
    return shares
