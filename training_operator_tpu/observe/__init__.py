"""Job-lifecycle observability: span tracer, describe surface, trace export.

Public surface:
  TimelineStore / JobTimeline / Span   the tracer model (observe/timeline.py)
  set_enabled / enabled                process-wide tracing switch
  export_chrome_trace                  Trace Event Format dump (observe/export.py)
  render_describe / phase_table        the describe renderer (observe/describe.py)

The APIServer owns a `TimelineStore` as `api.timelines`; instrumentation
in the admission path, the manager workqueue, the reconcile engine, and
the gang scheduler records into it. The wire exposes one job's timeline at
`GET /timelines/{ns}/{name}` and the registry text exposition at
`GET /metrics.txt`.
"""

from training_operator_tpu.observe.describe import (  # noqa: F401
    find_job,
    phase_table,
    render_describe,
)
from training_operator_tpu.observe.export import export_chrome_trace  # noqa: F401
from training_operator_tpu.observe.timeline import (  # noqa: F401
    JobTimeline,
    Span,
    TimelineStore,
    enabled,
    set_enabled,
)
