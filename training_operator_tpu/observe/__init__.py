"""Observability: span tracer, describe, trace export, fleet plane.

Public surface:
  TimelineStore / JobTimeline / Span   the tracer model (observe/timeline.py)
  set_enabled / enabled                process-wide tracing switch
  export_chrome_trace                  Trace Event Format dump (observe/export.py)
  render_describe / phase_table        the describe renderer (observe/describe.py)
  collect_fleet / FleetCollector /     the fleet snapshot plane
    render_top / FleetSources            (observe/fleet.py)
  InvariantAuditor / Violation /       the standing invariant auditor
    InvariantViolationError              (observe/invariants.py)

The APIServer owns a `TimelineStore` as `api.timelines`; instrumentation
in the admission path, the manager workqueue, the reconcile engine, and
the gang scheduler records into it. The wire exposes one job's timeline at
`GET /timelines/{ns}/{name}`, the fleet snapshot at `GET /fleet`, and the
registry text exposition at `GET /metrics.txt`.
"""

from training_operator_tpu.observe.attribution import (  # noqa: F401
    CAUSES,
    aggregate_queue_shares,
    attribute,
    explain,
    register_cause,
    render_explain,
)
from training_operator_tpu.observe.describe import (  # noqa: F401
    find_job,
    phase_table,
    render_describe,
)
from training_operator_tpu.observe.export import (  # noqa: F401
    export_chrome_trace,
    export_chrome_trace_merged,
)
from training_operator_tpu.observe.fleet import (  # noqa: F401
    FleetCollector,
    collect_fleet,
    render_top,
)
from training_operator_tpu.observe.invariants import (  # noqa: F401
    FleetSources,
    InvariantAuditor,
    InvariantViolationError,
    Violation,
)
from training_operator_tpu.observe.slo import (  # noqa: F401
    SLOEvaluator,
    SLOObjective,
    SLOPolicy,
    register_slo_admission,
    render_slo,
    validate_slo_policy,
)
from training_operator_tpu.observe.timeline import (  # noqa: F401
    JobTimeline,
    Span,
    TimelineStore,
    enabled,
    set_enabled,
)
