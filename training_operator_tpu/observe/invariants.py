"""Standing invariant auditor: "is the fleet healthy right now?" as code.

ROADMAP open item 4 names the invariant checker as the prerequisite for the
10k-node soak harness: chaos tiers prove jobs *converge*, but convergence
tests can't see a fleet that is quietly wrong in ways no single job notices
— an orphaned pod holding chips forever, a gang whose recorded placement no
longer matches any ICI mesh, an expectation entry that will gate reconciles
until its TTL on every pass. This module is the rule catalog plus the
periodic auditor that evaluates it against the live store.

Rule catalog (ALL rules are registered HERE — codelint CL006 rejects
`register_invariant` calls anywhere else, the CL005 pattern — so the README
reference table cannot drift against scattered registrations):

  INV001 orphaned-pod            a Pod labeled as owned by a job that no
                                 longer exists (cascade GC failed/wedged)
  INV002 gang-placement-broken   an admitted gang's recorded placement is
                                 inconsistent hardware: placed nodes gone /
                                 non-TPU, more slices than num_slices, or a
                                 non-contiguous host block (broken ICI mesh)
  INV003 stale-running-pod       a RUNNING pod on a dead/NotReady/vanished
                                 node past the eviction toleration (the
                                 node lifecycle controller failed to evict)
  INV004 wedged-expectation      an unfulfilled expectation older than the
                                 expectations TTL — its events will never
                                 arrive (the PR 5 expectation-leak class)
  INV005 storage-over-bound      host journal bytes past the compaction
                                 bound, or a resume ring holding more
                                 events than its configured size
  INV006 condition-disagreement  a terminal TrainJob whose same-named
                                 workload job holds the OPPOSITE terminal
                                 condition (v2 status sync broke)
  INV007 quota-over-admission    a ClusterQueue whose admitted gangs hold
                                 more of a quota'd resource than quota +
                                 borrowing allows (the arbiter's admission
                                 accounting broke, or a quota was shrunk
                                 below live usage and never reclaimed)
  INV008 replication-lag         a standby host whose WAL tail has fallen
                                 further behind the primary than
                                 replication_max_lag_seconds — failover
                                 from it would lose that much acknowledged
                                 history (the warm standby is cold)
  INV009 unbounded-accumulator   an in-memory accumulator (event store,
                                 timeline LRU, replication WAL ring,
                                 workqueue, ...) holding more entries than
                                 its configured bound — under sustained
                                 load it is growing without bound
  INV010 shard-ownership-broken  an operator reconcile shard claimed by
                                 two LIVE replicas at once (double-
                                 reconcile split brain), or unowned past
                                 `shard_takeover_grace` (death-handoff
                                 machinery failed; that slice of the
                                 fleet is not being reconciled)
  INV011 store-shard-ownership   an object readable from two WRITE shards
                                 (both journals claim its history — a
                                 replay would resurrect whichever copy
                                 loses), or held by a shard the
                                 (kind, namespace) routing map does not
                                 assign it to (router reads miss it)

Mechanics: every rule returns *candidates*; the auditor tracks first-seen
times and reports a violation only once it has persisted past the rule's
grace window (cluster-clock seconds) — transient in-between states (a
cascade delete one tick behind its job, a gang mid-invalidation) are the
normal operation of an asynchronous control plane, not violations. Reported
violations emit a Warning Event (deduplicated by the Event aggregation
path), increment `training_invariant_violations_total{rule}` once per
incident, land a timeline span on the affected job, and — in `fail_fast`
mode, which the chaos matrix and `bench.py --audit` run under — raise
`InvariantViolationError`, turning every existing chaos tier into an
invariant regression test.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from training_operator_tpu.utils import metrics

# Default audit cadence (OperatorConfig.fleet_audit_interval).
DEFAULT_AUDIT_INTERVAL = 30.0

# Grace windows (cluster-clock seconds a candidate must persist before it
# is a violation). Sized to the machinery that legitimately produces the
# transient: cascade GC and gang invalidation land within a tick or two but
# ride watch echoes and (remote) wire retries; eviction timers fire at the
# toleration deadline plus scheduling slack.
GRACE_TRANSIENT = 30.0


class InvariantViolationError(RuntimeError):
    """Raised by a fail-fast auditor when any violation is active."""


@dataclass
class Violation:
    rule: str
    object_kind: str
    namespace: str
    name: str
    message: str
    since: float = 0.0

    def key(self) -> Tuple[str, str, str, str]:
        return (self.rule, self.object_kind, self.namespace, self.name)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "object_kind": self.object_kind,
            "namespace": self.namespace,
            "name": self.name,
            "message": self.message,
            "since": self.since,
        }


@dataclass
class FleetSources:
    """Optional out-of-store signal feeds for the auditor and the fleet
    collector — state that lives beside the APIServer, not in it: the wire
    server knows its sessions and resume rings, the HostStore its journal,
    the manager its expectation caches. Every field is a zero-arg callable
    (or None when that subsystem isn't present in this deployment shape)."""

    journal_bytes: Optional[Callable[[], int]] = None
    journal_bound: Optional[Callable[[], int]] = None
    watch_sessions: Optional[Callable[[], int]] = None
    # kind -> (events retained, configured ring size)
    resume_ring: Optional[Callable[[], Dict[str, Tuple[int, int]]]] = None
    # unfulfilled expectation key -> age in cluster-clock seconds
    expectations: Optional[Callable[[], Dict[str, float]]] = None
    # StandbyController.lag(): {"role", "records", "seconds", "connected",
    # ...} — present only on a standby (or promoted ex-standby) host.
    replication_lag: Optional[Callable[[], Dict[str, Any]]] = None
    # Sharded operator ownership (INV010): the live replicas' shard-claim
    # records — {"num_shards": N, "grace": seconds, "claims": {identity:
    # [shard indices]}} — aggregated from each OperatorManager.shard_claims
    # (one per live replica in this deployment). The shard LEASES live in
    # the store (controllers/leader.py) and carry the unowned-age evidence;
    # the claims carry what no lease can express — two live replicas both
    # believing they own one shard.
    shards: Optional[Callable[[], Dict[str, Any]]] = None
    # Generic bounded-accumulator feed (INV009): name -> (size, bound) for
    # every in-memory accumulator this deployment shape is supposed to keep
    # ring/cap-bounded — the event store, the timeline LRU, the replication
    # WAL ring, the manager workqueue, ... . INV005 audits the two storage
    # structures with their own protocols (journal bytes, resume rings);
    # this feed catches the rest, so "nothing grows without bound over a
    # simulated week" is one rule, not a scattering of ad-hoc asserts.
    accumulators: Optional[Callable[[], Dict[str, Tuple[int, int]]]] = None
    # Sharded write plane (INV011): the StoreShardSet's ownership_report()
    # (or the wire router's equivalent) — {"num_shards": N, "meta_shard":
    # i, "counts": {shard: live keys}, "duplicates": [(i, j, key), ...],
    # "misrouted": [(i, key), ...]}. A duplicate is an object readable
    # from two shards (split-brain durability: two journals both claim its
    # history); a misrouted key is held by a shard the (kind, namespace)
    # map does not assign it to, so a router-side read would miss it.
    store_shards: Optional[Callable[[], Dict[str, Any]]] = None
    # SLO engine (observe/slo.py): the evaluator's evaluate() — one call
    # per fleet tick scores every stored SLOPolicy, republishes the
    # training_slo_* gauges, and returns the `slo` section collect_fleet
    # embeds. None when the deployment shape has no evaluator.
    slo: Optional[Callable[[], Dict[str, Any]]] = None


class AuditContext:
    """One audit pass's view: the store, the clock instant, and the side
    sources — with the object lists fetched once and shared across rules
    (list_refs: frozen references, no clones)."""

    def __init__(self, api, now: float, sources: Optional[FleetSources],
                 toleration_seconds: float):
        self.api = api
        self.now = now
        self.sources = sources or FleetSources()
        self.toleration_seconds = toleration_seconds
        self._lists: Dict[str, List[Any]] = {}
        self._nodes_by_name: Optional[Dict[str, Any]] = None

    def list(self, kind: str) -> List[Any]:
        cached = self._lists.get(kind)
        if cached is None:
            cached = self._lists[kind] = list(self.api.list_refs(kind))
        return cached

    def nodes_by_name(self) -> Dict[str, Any]:
        if self._nodes_by_name is None:
            self._nodes_by_name = {
                n.metadata.name: n for n in self.list("Node")
            }
        return self._nodes_by_name


@dataclass
class InvariantRule:
    rule_id: str
    description: str
    check: Callable[[AuditContext], List[Violation]]
    grace: float = GRACE_TRANSIENT


RULES: List[InvariantRule] = []


def register_invariant(rule: InvariantRule) -> InvariantRule:
    """THE registration point (CL006): every rule the auditor can evaluate
    is declared in this module, so the rule-id catalog is one greppable
    list and a duplicate id is impossible to introduce silently."""
    if any(r.rule_id == rule.rule_id for r in RULES):
        raise ValueError(f"invariant rule {rule.rule_id} already registered")
    RULES.append(rule)
    return rule


# ---------------------------------------------------------------------------
# Rule checks
# ---------------------------------------------------------------------------


def _check_orphaned_pods(ctx: AuditContext) -> List[Violation]:
    from training_operator_tpu.api.common import JOB_KIND_LABEL, JOB_NAME_LABEL

    out = []
    for pod in ctx.list("Pod"):
        labels = pod.metadata.labels
        jkind = labels.get(JOB_KIND_LABEL)
        jname = labels.get(JOB_NAME_LABEL)
        if not jkind or not jname:
            continue
        if ctx.api.resource_version(jkind, pod.namespace, jname) is None:
            out.append(Violation(
                "INV001", "Pod", pod.namespace, pod.metadata.name,
                f"pod {pod.namespace}/{pod.metadata.name} has no live "
                f"owning {jkind} {jname} (cascade GC missed it)",
            ))
    return out


def _check_gang_placement(ctx: AuditContext) -> List[Violation]:
    from training_operator_tpu.cluster.objects import PodGroupPhase
    from training_operator_tpu.scheduler.snapshot import (
        contiguous_host_block,
        host_index,
    )

    nodes = ctx.nodes_by_name()
    out = []
    for pg in ctx.list("PodGroup"):
        if pg.phase not in (PodGroupPhase.INQUEUE, PodGroupPhase.RUNNING):
            continue
        if not pg.placement or not pg.topology_request:
            continue  # non-TPU gang: no ICI contract to audit
        problems: List[str] = []
        slices: Dict[str, List[int]] = {}
        for pod_name, node_name in sorted(pg.placement.items()):
            node = nodes.get(node_name)
            if node is None:
                problems.append(f"placed node {node_name} no longer exists")
                continue
            acc = node.accelerator
            if acc.kind != "tpu" or not acc.tpu_slice:
                problems.append(
                    f"pod {pod_name} placed on non-TPU node {node_name}"
                )
                continue
            slices.setdefault(acc.tpu_slice, []).append(host_index(node))
        budget = max(1, pg.num_slices)
        if len(slices) > budget:
            problems.append(
                f"gang spans {len(slices)} failure domains "
                f"({', '.join(sorted(slices))}) > num_slices={budget}"
            )
        for sid in sorted(slices):
            if not contiguous_host_block(slices[sid]):
                problems.append(
                    f"hosts {sorted(set(slices[sid]))} in slice {sid} are "
                    f"not an ICI-contiguous block"
                )
        if problems:
            out.append(Violation(
                "INV002", "PodGroup", pg.namespace, pg.metadata.name,
                "; ".join(problems),
            ))
    return out


def _check_stale_running_pods(ctx: AuditContext) -> List[Violation]:
    from training_operator_tpu.cluster.objects import (
        NODE_CONDITION_READY,
        PodPhase,
        get_node_condition,
    )

    nodes = ctx.nodes_by_name()
    tol = ctx.toleration_seconds
    out = []
    for pod in ctx.list("Pod"):
        if pod.status.phase != PodPhase.RUNNING or not pod.node_name:
            continue
        node = nodes.get(pod.node_name)
        if node is None:
            out.append(Violation(
                "INV003", "Pod", pod.namespace, pod.metadata.name,
                f"RUNNING pod on vanished node {pod.node_name}",
            ))
            continue
        cond = get_node_condition(node, NODE_CONDITION_READY)
        if cond is None or cond.get("status") == "True":
            continue
        age = ctx.now - float(cond.get("last_transition_time", ctx.now))
        if age > tol:
            out.append(Violation(
                "INV003", "Pod", pod.namespace, pod.metadata.name,
                f"RUNNING pod on NotReady node {pod.node_name} for "
                f"{age:.0f}s > toleration {tol:.0f}s (eviction missed it)",
            ))
    return out


def _check_wedged_expectations(ctx: AuditContext) -> List[Violation]:
    from training_operator_tpu.engine.expectations import (
        EXPECTATION_TIMEOUT_SECONDS,
    )

    src = ctx.sources.expectations
    if src is None:
        return []
    out = []
    for key, age in src().items():
        if age > EXPECTATION_TIMEOUT_SECONDS:
            out.append(Violation(
                "INV004", "Expectation", "", key,
                f"expectation {key} unfulfilled for {age:.0f}s > TTL "
                f"{EXPECTATION_TIMEOUT_SECONDS:.0f}s — its watch events "
                f"will never arrive",
            ))
    return out


def _check_storage_bounds(ctx: AuditContext) -> List[Violation]:
    out = []
    src = ctx.sources
    if src.journal_bytes is not None and src.journal_bound is not None:
        bound = int(src.journal_bound())
        size = int(src.journal_bytes())
        if bound > 0 and size > bound:
            out.append(Violation(
                "INV005", "HostStore", "", "journal",
                f"journal holds {size} bytes > compaction bound {bound} "
                f"(compaction wedged?)",
            ))
    if src.resume_ring is not None:
        for kind, (occupancy, size) in sorted(src.resume_ring().items()):
            if occupancy > size:
                out.append(Violation(
                    "INV005", "ResumeRing", "", kind,
                    f"resume ring for {kind} retains {occupancy} events > "
                    f"configured size {size}",
                ))
    return out


def _check_condition_disagreement(ctx: AuditContext) -> List[Violation]:
    from training_operator_tpu.api import common as capi
    from training_operator_tpu.api.jobs import JOB_KINDS
    from training_operator_tpu.runtime.api import TrainJobConditionType

    # Same-named workload jobs of every v1 kind, indexed once.
    workloads: Dict[Tuple[str, str], Any] = {}
    for kind in JOB_KINDS:
        for job in ctx.list(kind):
            workloads[(job.namespace, job.metadata.name)] = job
    out = []
    for tj in ctx.list("TrainJob"):
        complete = tj.condition(TrainJobConditionType.COMPLETE)
        failed = tj.condition(TrainJobConditionType.FAILED)
        tj_state = None
        if complete is not None and complete.status:
            tj_state = "Complete"
        elif failed is not None and failed.status:
            tj_state = "Failed"
        if tj_state is None:
            continue
        wj = workloads.get((tj.namespace, tj.metadata.name))
        if wj is None:
            continue  # workload GC'd after terminal sync: consistent
        wj_failed = capi.has_condition(wj.status, capi.JobConditionType.FAILED)
        wj_succeeded = capi.is_succeeded(wj.status)
        if (tj_state == "Complete" and wj_failed) or (
            tj_state == "Failed" and wj_succeeded
        ):
            out.append(Violation(
                "INV006", "TrainJob", tj.namespace, tj.metadata.name,
                f"TrainJob is {tj_state} but workload {wj.kind} "
                f"{wj.namespace}/{wj.metadata.name} holds the opposite "
                f"terminal condition",
            ))
    return out


register_invariant(InvariantRule(
    "INV001", "pod with no live owning job", _check_orphaned_pods,
))
register_invariant(InvariantRule(
    "INV002",
    "admitted gang placement split across failure domains or ICI-broken",
    _check_gang_placement,
))
register_invariant(InvariantRule(
    "INV003", "RUNNING pod on a dead node past its eviction toleration",
    _check_stale_running_pods,
))
register_invariant(InvariantRule(
    "INV004", "expectation unfulfilled past its TTL",
    _check_wedged_expectations, grace=0.0,  # the TTL IS the grace
))
register_invariant(InvariantRule(
    "INV005", "journal or resume ring over its configured bound",
    _check_storage_bounds, grace=60.0,  # compaction runs from the host loop
))
def _check_quota_over_admission(ctx: AuditContext) -> List[Violation]:
    # THE accounting is tenancy/arbiter.admitted_usage — the same function
    # the arbiter admits against and the fleet gauges publish, so the
    # auditor can only fire when the bound itself is broken, never from a
    # parallel reimplementation drifting.
    from training_operator_tpu.tenancy.arbiter import admitted_usage

    queues = {q.metadata.name: q for q in ctx.list("ClusterQueue")}
    if not queues:
        return []
    usage = admitted_usage(ctx.list("PodGroup"), queues)
    out = []
    for name in sorted(queues):
        q = queues[name]
        held = usage.get(name, {})
        over = [
            f"{res}: {held.get(res, 0.0):g} > {q.cap(res):g} "
            f"(quota {q.quota.get(res, 0.0):g} + borrowing "
            f"{q.borrowing_limit.get(res, 0.0):g})"
            for res in sorted(q.quota)
            if held.get(res, 0.0) > q.cap(res) + 1e-9
        ]
        if over:
            out.append(Violation(
                "INV007", "ClusterQueue", "", name,
                "admitted gangs exceed quota + borrowing — " + "; ".join(over),
            ))
    return out


register_invariant(InvariantRule(
    "INV006", "TrainJob and workload job disagree on the terminal condition",
    _check_condition_disagreement, grace=60.0,  # one v2 resync heals it
))
register_invariant(InvariantRule(
    "INV007", "queue admitted usage exceeds quota + borrowing",
    _check_quota_over_admission,
))


def _check_replication_lag(ctx: AuditContext) -> List[Violation]:
    from training_operator_tpu import config

    src = ctx.sources.replication_lag
    if src is None:
        return []
    lag = src()
    if lag.get("role") != "standby":
        return []  # a promoted ex-standby is the primary: nothing to lag
    bound = config.current().replication_max_lag_seconds
    seconds = float(lag.get("seconds", 0.0))
    if bound > 0 and seconds > bound:
        return [Violation(
            "INV008", "Replication", "", "wal-tail",
            f"standby replication lag {seconds:.1f}s > "
            f"replication_max_lag_seconds {bound:.1f}s "
            f"({int(lag.get('records', 0))} records behind, "
            f"connected={bool(lag.get('connected'))}) — failover from this "
            f"standby would lose that much acknowledged history",
        )]
    return []


register_invariant(InvariantRule(
    "INV008", "standby replication lag over replication_max_lag_seconds",
    # replication_max_lag_seconds IS the grace (the INV004 TTL pattern):
    # the candidate only exists once lag has already persisted past the
    # configured bound, so a second grace window would double-count it.
    _check_replication_lag, grace=0.0,
))


def _check_unbounded_accumulators(ctx: AuditContext) -> List[Violation]:
    src = ctx.sources.accumulators
    if src is None:
        return []
    out = []
    for name, (size, bound) in sorted(src().items()):
        if bound > 0 and size > bound:
            out.append(Violation(
                "INV009", "Accumulator", "", name,
                f"accumulator {name} holds {int(size)} entries > configured "
                f"bound {int(bound)} — it is growing without bound "
                f"(retention/trim machinery broke, or the bound was set "
                f"below live steady state)",
            ))
    return out


register_invariant(InvariantRule(
    "INV009", "in-memory accumulator over its configured bound",
    # Every audited accumulator trims synchronously at its cap (event
    # store, timeline LRU, WAL ring, ...), so even one pass over the bound
    # means the trim machinery itself failed; the transient grace only
    # absorbs feeds sampled mid-burst (e.g. a workqueue drained per tick).
    _check_unbounded_accumulators,
))


def _check_shard_ownership(ctx: AuditContext) -> List[Violation]:
    """INV010, the sharded-operator ownership contract, both directions:

      split-brain   a shard claimed by >= 2 LIVE replicas at once — two
                    reconcilers writing one job's status/pods (the lease
                    CAS should make this impossible; a replica that kept
                    claiming after losing its lease is exactly the bug)
      orphaned      a shard no live replica claims whose lease has been
                    expired longer than `shard_takeover_grace` — the
                    death-handoff machinery failed and that slice of the
                    fleet is not being reconciled by anyone

    The double-claim side reads the live claims feed (a dead replica
    cannot claim); the unowned side reads lease ages from the store, so
    "past the grace" is lease arithmetic, not audit-cadence luck."""
    from training_operator_tpu.controllers.leader import (
        SHARD_NAMESPACE,
        shard_lease_name,
    )

    src = ctx.sources.shards
    if src is None:
        return []
    info = src()
    n = int(info.get("num_shards", 0))
    claims: Dict[str, Any] = info.get("claims", {}) or {}
    if n <= 1 or not claims:
        return []  # unsharded, or no live replicas to hold anything
    grace = float(info.get("grace", 10.0))
    by_shard: Dict[int, List[str]] = {}
    for identity, shards in claims.items():
        for s in shards:
            by_shard.setdefault(int(s), []).append(identity)
    out: List[Violation] = []
    for s in sorted(by_shard):
        owners = sorted(by_shard[s])
        if len(owners) > 1:
            out.append(Violation(
                "INV010", "Shard", "", f"shard-{s}",
                f"shard {s} claimed by {len(owners)} live replicas "
                f"({', '.join(owners)}) — double-reconcile split brain",
            ))
    for s in range(n):
        if by_shard.get(s):
            continue
        lease = ctx.api.try_get("Lease", SHARD_NAMESPACE, shard_lease_name(s))
        if lease is None:
            # Never owned at all while replicas are live: the bootstrap
            # window; the rule grace absorbs it, persistence condemns it.
            out.append(Violation(
                "INV010", "Shard", "", f"shard-{s}",
                f"shard {s} has no lease and no live claimant "
                f"({len(claims)} replicas alive)",
            ))
            continue
        # `renew_time + duration` is the instant the shard became
        # adoptable: lease expiry for a dead holder, the release instant
        # for a voluntary handoff (release() backdates by exactly one
        # duration) — either way, older than the grace means the takeover/
        # pickup machinery failed.
        expiry = lease.renew_time + lease.lease_duration
        unowned_for = ctx.now - expiry
        if lease.expired(ctx.now) and unowned_for > grace:
            out.append(Violation(
                "INV010", "Shard", "", f"shard-{s}",
                f"shard {s} unowned for {unowned_for:.1f}s past "
                f"{'release' if not lease.holder else 'lease expiry'} > "
                f"shard_takeover_grace {grace:.1f}s (last holder "
                f"{lease.holder or '<released>'}; takeover machinery "
                f"failed) — its namespaces are not being reconciled",
            ))
    return out


register_invariant(InvariantRule(
    "INV010",
    "operator shard owned by two live replicas, or unowned past the grace",
    # The transient grace absorbs legitimate handoff windows: a losing
    # replica claims until its next tick observes the lost lease, and a
    # dying one's shards are honestly unowned for up to takeover_grace
    # (which the unowned arm already discounts via lease arithmetic).
    _check_shard_ownership,
))


def _check_store_shard_ownership(ctx: AuditContext) -> List[Violation]:
    """INV011, the sharded WRITE plane's ownership contract: no object is
    readable from two store shards, and every shard holds only the keys
    the (kind, namespace) routing map assigns to it. The feed is the
    StoreShardSet's `ownership_report()` — per-shard live-key counts, the
    exact duplicate keys (an object whose history two journals both
    claim: a replay would resurrect whichever copy loses the race), and a
    bounded misroute spot check (a key a router-side read would miss,
    because it asks the shard the map points at)."""
    src = ctx.sources.store_shards
    if src is None:
        return []
    info = src()
    if int(info.get("num_shards", 0)) <= 1:
        return []  # unsharded plane: nothing to disagree about
    out: List[Violation] = []
    for i, j, key in info.get("duplicates", []) or []:
        kind, ns, name = key
        out.append(Violation(
            "INV011", kind, ns, name,
            f"object readable from store shards {i} and {j} — two "
            f"journals claim its history (split-brain durability)",
        ))
    for i, key in info.get("misrouted", []) or []:
        kind, ns, name = key
        out.append(Violation(
            "INV011", kind, ns, name,
            f"object held by store shard {i} but the (kind, namespace) "
            f"map routes it elsewhere — router reads miss it",
        ))
    return out


register_invariant(InvariantRule(
    "INV011",
    "object readable from two store shards, or held off its mapped shard",
    # The routing sink assigns each mutation to exactly one shard under
    # the APIServer lock, so even a single observation is machinery
    # failure; the transient grace only absorbs a feed sampled mid
    # per-shard failover (store adoption swaps the shard slot atomically).
    _check_store_shard_ownership,
))


# Violation targets whose (namespace, name) IS a job timeline key — only
# these get a span (a span per orphaned pod would pollute the job ring with
# pod-named timelines).
_SPAN_KINDS = ("PodGroup", "TrainJob")


class InvariantAuditor:
    """Evaluates the rule catalog periodically against one APIServer.

    `now_fn` is the cluster clock, so graces and cadence run in sim time on
    a virtual clock (the chaos matrix) and in wall time on a host. `audit()`
    is also directly callable — the bench calls it per tick."""

    def __init__(
        self,
        api,
        now_fn: Callable[[], float],
        sources: Optional[FleetSources] = None,
        interval: float = DEFAULT_AUDIT_INTERVAL,
        fail_fast: bool = False,
        toleration_seconds: Optional[float] = None,
        rules: Optional[List[InvariantRule]] = None,
    ):
        from training_operator_tpu import config

        self.api = api
        self.now = now_fn
        self.sources = sources or FleetSources()
        self.interval = interval
        self.fail_fast = fail_fast
        self.toleration_seconds = (
            toleration_seconds
            if toleration_seconds is not None
            else config.current().node_toleration_seconds
        )
        self.rules = list(rules if rules is not None else RULES)
        # Candidate key -> first-seen cluster time (grace tracking).
        self._first_seen: Dict[Tuple, float] = {}
        # Keys currently reported: the counter/Event/span fire once per
        # incident, not once per audit pass; a healed-then-recurring key
        # counts again.
        self._reported: set = set()
        self.last_violations: List[Violation] = []
        # Audit generation — the /fleet byte cache keys on (store version,
        # seq) so a fresh audit invalidates the cached snapshot.
        self.seq = 0
        self.audits = 0
        self._armed = False

    # -- evaluation ----------------------------------------------------

    def audit(self) -> List[Violation]:
        now = self.now()
        ctx = AuditContext(self.api, now, self.sources, self.toleration_seconds)
        candidates: Dict[Tuple, Tuple[InvariantRule, Violation]] = {}
        for rule in self.rules:
            for v in rule.check(ctx):
                candidates[v.key()] = (rule, v)
        # Healed candidates reset their grace clock (and their incident).
        for key in list(self._first_seen):
            if key not in candidates:
                del self._first_seen[key]
        active: List[Violation] = []
        for key, (rule, v) in candidates.items():
            first = self._first_seen.setdefault(key, now)
            if now - first < rule.grace:
                continue
            v.since = first
            active.append(v)
            if key not in self._reported:
                self._reported.add(key)
                self._report(v, now)
        self._reported &= set(candidates)
        active.sort(key=lambda v: v.key())
        self.last_violations = active
        metrics.fleet_violations.set(value=float(len(active)))
        self.seq += 1
        self.audits += 1
        if self.fail_fast and active:
            raise InvariantViolationError(
                "; ".join(f"{v.rule} {v.object_kind} {v.namespace}/{v.name}: "
                          f"{v.message}" for v in active)
            )
        return active

    def _report(self, v: Violation, now: float) -> None:
        from training_operator_tpu.cluster.objects import Event

        metrics.invariant_violations.inc(v.rule)
        self.api.record_event(Event(
            object_kind=v.object_kind,
            object_name=v.name,
            namespace=v.namespace,
            event_type="Warning",
            reason=v.rule,
            message=v.message,
            timestamp=now,
        ))
        if v.object_kind in _SPAN_KINDS:
            self.api.timelines.record_span(
                v.namespace, v.name, "", "invariant",
                start=v.since, end=now, rule=v.rule, message=v.message,
            )

    # -- periodic ------------------------------------------------------

    def attach(self, cluster) -> "InvariantAuditor":
        """Run on the cluster's (virtual) clock every `interval` — the
        standing auditor. In fail-fast mode a violation raises out of the
        timer callback through `Cluster.step()`, failing the run."""
        self._armed = True
        cluster.schedule_after(self.interval, lambda: self._tick(cluster))
        return self

    def detach(self) -> None:
        self._armed = False

    def _tick(self, cluster) -> None:
        if not self._armed:
            return
        try:
            self.audit()
        finally:
            if self._armed:
                cluster.schedule_after(
                    self.interval, lambda: self._tick(cluster)
                )
