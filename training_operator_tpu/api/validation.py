"""Admission-time validation.

Parity target: reference pkg/webhooks/<fw>/<fw>_webhook.go validators and
pkg/common/util/webhooks.go:15-27 (RunPolicy validation), plus
mpi_validation.go:69. The reference runs these as validating admission
webhooks; here they are a pure function invoked by the API server on
create/update and available to the SDK for client-side checks.
"""

from __future__ import annotations

import re
from typing import List

from training_operator_tpu.api.defaults import DEFAULT_CONTAINER_NAME
from training_operator_tpu.api.jobs import (
    JOB_KINDS,
    Job,
    MPIJob,
    PyTorchJob,
    TFJob,
    replica_types_for_kind,
)

# RFC 1035 label: what the reference enforces on job names so the generated
# pod/service DNS names are valid (e.g. pytorchjob_webhook.go:44-60).
_DNS1035 = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")
_MAX_NAME_LEN = 63


def is_dns1035_label(name: str) -> bool:
    """The one copy of the name rule (webhooks and the spec analyzer must
    agree with v1 admission about what a legal name is)."""
    return bool(_DNS1035.match(name)) and len(name) <= _MAX_NAME_LEN


class ValidationError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


def validate_job(job: Job) -> None:
    """Raise ValidationError listing every problem found."""
    errs: List[str] = []

    if not job.metadata.name:
        errs.append("metadata.name: required")
    elif not is_dns1035_label(job.metadata.name):
        errs.append(
            f"metadata.name: {job.metadata.name!r} must be a valid RFC1035 label "
            f"(lowercase alphanumeric/'-', start with a letter, <={_MAX_NAME_LEN} chars)"
        )

    if not job.replica_specs:
        errs.append("replicaSpecs: at least one replica type required")

    valid_types = set(replica_types_for_kind(job.kind)) if job.kind in JOB_KINDS else None
    default_container = DEFAULT_CONTAINER_NAME.get(job.kind, "trainer")

    for rtype, spec in job.replica_specs.items():
        path = f"replicaSpecs[{rtype}]"
        if valid_types is not None and rtype not in valid_types:
            errs.append(f"{path}: invalid replica type for {job.kind}; valid: {sorted(valid_types)}")
        if spec.replicas is not None and spec.replicas < 0:
            errs.append(f"{path}.replicas: must be >= 0")
        if not spec.template.containers:
            errs.append(f"{path}.template.containers: required")
            continue
        names = [c.name for c in spec.template.containers]
        if default_container not in names:
            errs.append(
                f"{path}.template.containers: must contain a container named "
                f"{default_container!r} (got {names})"
            )
        for c in spec.template.containers:
            if not c.image:
                errs.append(f"{path}.template.containers[{c.name}].image: required")

    _validate_run_policy(job, errs)
    _validate_kind_specific(job, errs)
    _validate_tpu_policy(job, errs)

    if errs:
        raise ValidationError(errs)


def _validate_run_policy(job: Job, errs: List[str]) -> None:
    """Reference pkg/common/util/webhooks.go:15-27."""
    rp = job.run_policy
    if rp.backoff_limit is not None and rp.backoff_limit < 0:
        errs.append("runPolicy.backoffLimit: must be >= 0")
    if rp.active_deadline_seconds is not None and rp.active_deadline_seconds < 0:
        errs.append("runPolicy.activeDeadlineSeconds: must be >= 0")
    if rp.ttl_seconds_after_finished is not None and rp.ttl_seconds_after_finished < 0:
        errs.append("runPolicy.ttlSecondsAfterFinished: must be >= 0")
    if rp.scheduling_policy and rp.scheduling_policy.min_available is not None:
        if rp.scheduling_policy.min_available < 0:
            errs.append("runPolicy.schedulingPolicy.minAvailable: must be >= 0")


def _validate_kind_specific(job: Job, errs: List[str]) -> None:
    if isinstance(job, PyTorchJob):
        ep = job.elastic_policy
        if ep is not None:
            if ep.min_replicas is not None and ep.min_replicas < 0:
                errs.append("elasticPolicy.minReplicas: must be >= 0")
            if (
                ep.min_replicas is not None
                and ep.max_replicas is not None
                and ep.max_replicas < ep.min_replicas
            ):
                errs.append("elasticPolicy.maxReplicas: must be >= minReplicas")
        if job.nproc_per_node is not None and job.nproc_per_node < 1:
            errs.append("nprocPerNode: must be >= 1")
    elif isinstance(job, TFJob):
        # Chief and Master are semantically equivalent; at most one of each.
        for t in ("Chief", "Master"):
            spec = job.replica_specs.get(t)
            if spec is not None and (spec.replicas or 0) > 1:
                errs.append(f"replicaSpecs[{t}].replicas: must be <= 1")
        if "Chief" in job.replica_specs and "Master" in job.replica_specs:
            errs.append("replicaSpecs: at most one of Chief/Master may be set")
    elif isinstance(job, MPIJob):
        # Reference mpi_validation.go:69 — exactly one launcher.
        launcher = job.replica_specs.get("Launcher")
        if launcher is None:
            errs.append("replicaSpecs[Launcher]: required for MPIJob")
        elif (launcher.replicas or 0) > 1:
            errs.append("replicaSpecs[Launcher].replicas: must be <= 1")
        if job.slots_per_worker < 1:
            errs.append("slotsPerWorker: must be >= 1")


def _validate_tpu_policy(job: Job, errs: List[str]) -> None:
    tp = job.tpu_policy
    if tp is None:
        return
    if tp.num_slices < 1:
        errs.append("tpuPolicy.numSlices: must be >= 1")
    elif tp.num_slices > 1:
        # Multi-slice gangs split workers contiguously across slices (the
        # packer's placement convention and the per-slice bootstrap env both
        # assume it); an indivisible worker count can never be placed.
        total = sum(spec.replicas or 0 for spec in job.replica_specs.values())
        if total and total % tp.num_slices:
            errs.append(
                f"tpuPolicy.numSlices: total replicas {total} must be divisible "
                f"by numSlices {tp.num_slices}"
            )
    if tp.topology is not None:
        if not re.match(r"^[1-9]\d*(x[1-9]\d*)*$", tp.topology.lower()):
            errs.append(
                f"tpuPolicy.topology: {tp.topology!r} must look like '2x4' with positive dims"
            )
        else:
            # Cross-check against the accelerator's chip count when it has one
            # (e.g. "v5e-8"): topology must tile exactly those chips.
            try:
                accel_chips = int(tp.accelerator.rsplit("-", 1)[1])
            except (IndexError, ValueError):
                accel_chips = None
            if accel_chips is not None:
                topo_chips = 1
                for x in tp.topology.lower().split("x"):
                    topo_chips *= int(x)
                if topo_chips != accel_chips:
                    errs.append(
                        f"tpuPolicy.topology: {tp.topology!r} has {topo_chips} chips but "
                        f"accelerator {tp.accelerator!r} has {accel_chips}"
                    )
    if tp.mesh_axes:
        prod = 1
        for v in tp.mesh_axes.values():
            prod *= v
        if prod != tp.total_chips():
            errs.append(
                f"tpuPolicy.meshAxes: product {prod} must equal total chips "
                f"{tp.total_chips()} ({tp.num_slices} slice(s) x {tp.chips_per_slice()})"
            )
