"""Common job API types shared by every job kind.

Parity target: reference pkg/apis/kubeflow.org/v1/common_types.go:24-251 —
JobStatus / ReplicaSpec / ReplicaStatus / JobCondition / RunPolicy /
RestartPolicy / CleanPodPolicy / SchedulingPolicy — re-designed as Python
dataclasses. Serialization (`to_dict` / `from_dict`) replaces the reference's
generated deepcopy/openapi machinery.

Label keys mirror reference common_types.go:24-44 under our own API group.
"""

from __future__ import annotations

import copy
import dataclasses
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Well-known labels (reference common_types.go:24-44)
# ---------------------------------------------------------------------------
LABEL_PREFIX = "training.tpu.dev/"
REPLICA_INDEX_LABEL = LABEL_PREFIX + "replica-index"
REPLICA_TYPE_LABEL = LABEL_PREFIX + "replica-type"
JOB_NAME_LABEL = LABEL_PREFIX + "job-name"
JOB_KIND_LABEL = LABEL_PREFIX + "job-kind"
JOB_ROLE_LABEL = LABEL_PREFIX + "job-role"
OPERATOR_NAME_LABEL = LABEL_PREFIX + "operator-name"
JOB_ROLE_MASTER = "master"


class RestartPolicy(str, enum.Enum):
    """Restart policy for replicas (reference common_types.go:183-189).

    EXIT_CODE: exit codes 1-127 are permanent failures; >=128 (signal-killed,
    e.g. SIGKILL from preemption) are retryable (reference
    pkg/util/train/train_util.go:14).
    """

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    EXIT_CODE = "ExitCode"


def is_retryable_exit_code(code: int) -> bool:
    """Reference pkg/util/train/train_util.go:14 — >=128 means killed by signal."""
    return code >= 128


class CleanPodPolicy(str, enum.Enum):
    """What to clean up when the job finishes (reference common_types.go)."""

    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


class JobConditionType(str, enum.Enum):
    """Job condition types (reference common_types.go:47-76)."""

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    SUSPENDED = "Suspended"
    FAILED = "Failed"


@dataclass
class JobCondition:
    type: JobConditionType
    status: bool
    reason: str = ""
    message: str = ""
    last_update_time: float = 0.0
    last_transition_time: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "type": self.type.value,
            "status": self.status,
            "reason": self.reason,
            "message": self.message,
            "lastUpdateTime": self.last_update_time,
            "lastTransitionTime": self.last_transition_time,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "JobCondition":
        return cls(
            type=JobConditionType(d["type"]),
            status=bool(d["status"]),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            last_update_time=d.get("lastUpdateTime", 0.0),
            last_transition_time=d.get("lastTransitionTime", 0.0),
        )


@dataclass
class ReplicaStatus:
    """Per-replica-type tallies (reference common_types.go ReplicaStatus)."""

    active: int = 0
    succeeded: int = 0
    failed: int = 0
    label_selector: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "active": self.active,
            "succeeded": self.succeeded,
            "failed": self.failed,
            "labelSelector": self.label_selector,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaStatus":
        return cls(
            active=d.get("active", 0),
            succeeded=d.get("succeeded", 0),
            failed=d.get("failed", 0),
            label_selector=d.get("labelSelector", ""),
        )


@dataclass
class JobStatus:
    """Observed job state (reference common_types.go JobStatus)."""

    conditions: List[JobCondition] = field(default_factory=list)
    replica_statuses: Dict[str, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "conditions": [c.to_dict() for c in self.conditions],
            "replicaStatuses": {k: v.to_dict() for k, v in self.replica_statuses.items()},
            "startTime": self.start_time,
            "completionTime": self.completion_time,
            "lastReconcileTime": self.last_reconcile_time,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "JobStatus":
        return cls(
            conditions=[JobCondition.from_dict(c) for c in d.get("conditions", [])],
            replica_statuses={
                k: ReplicaStatus.from_dict(v) for k, v in d.get("replicaStatuses", {}).items()
            },
            start_time=d.get("startTime"),
            completion_time=d.get("completionTime"),
            last_reconcile_time=d.get("lastReconcileTime"),
        )


# ---------------------------------------------------------------------------
# Condition helpers (reference pkg/util/status.go, pkg/core/status.go:25-50)
# ---------------------------------------------------------------------------


def get_condition(status: JobStatus, cond_type: JobConditionType) -> Optional[JobCondition]:
    for c in status.conditions:
        if c.type == cond_type:
            return c
    return None


def has_condition(status: JobStatus, cond_type: JobConditionType) -> bool:
    c = get_condition(status, cond_type)
    return c is not None and c.status


def is_finished(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.SUCCEEDED) or has_condition(
        status, JobConditionType.FAILED
    )

def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.FAILED)


def is_suspended(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.SUSPENDED)


def is_running(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.RUNNING)


def update_job_conditions(
    status: JobStatus,
    cond_type: JobConditionType,
    cond_status: bool,
    reason: str,
    message: str,
    now: Optional[float] = None,
) -> None:
    """Set/append a condition, keeping mutual exclusion between phase conditions.

    Mirrors reference pkg/util/status.go UpdateJobConditions semantics: setting
    Running clears Restarting; setting a terminal/Restarting condition clears
    Running; duplicate updates only bump lastUpdateTime.
    """
    now = time.time() if now is None else now
    new_cond = JobCondition(
        type=cond_type,
        status=cond_status,
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )
    if cond_status and cond_type in (
        JobConditionType.RUNNING,
        JobConditionType.SUCCEEDED,
        JobConditionType.FAILED,
    ):
        # Phase conditions are mutually exclusive with Restarting/Suspended.
        _filter_out(status, JobConditionType.RESTARTING)
        _filter_out(status, JobConditionType.SUSPENDED)
        if cond_type != JobConditionType.RUNNING:
            _filter_out(status, JobConditionType.RUNNING)
    if cond_status and cond_type in (
        JobConditionType.RESTARTING,
        JobConditionType.SUSPENDED,
    ):
        _filter_out(status, JobConditionType.RUNNING)

    existing = get_condition(status, cond_type)
    if existing is not None:
        if existing.status == new_cond.status and existing.reason == new_cond.reason:
            # True no-op updates leave the condition untouched so reconcile
            # passes that change nothing produce byte-identical status (the
            # engine skips the API write in that case).
            if existing.message != message:
                existing.message = message
                existing.last_update_time = now
            return
        new_cond.last_transition_time = now
        status.conditions = [c for c in status.conditions if c.type != cond_type]
    status.conditions.append(new_cond)


def _filter_out(status: JobStatus, cond_type: JobConditionType) -> None:
    status.conditions = [c for c in status.conditions if c.type != cond_type]


# ---------------------------------------------------------------------------
# Scheduling & run policy
# ---------------------------------------------------------------------------


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (reference common_types.go SchedulingPolicy).

    `topology` is the TPU-first extension: a requested ICI mesh shape, e.g.
    "2x4" for a v5e-8 slice, consumed by the tpu-packer placement engine.
    """

    min_available: Optional[int] = None
    queue: str = ""
    min_resources: Dict[str, float] = field(default_factory=dict)
    priority_class: str = ""
    schedule_timeout_seconds: Optional[int] = None
    topology: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "minAvailable": self.min_available,
            "queue": self.queue,
            "minResources": dict(self.min_resources),
            "priorityClass": self.priority_class,
            "scheduleTimeoutSeconds": self.schedule_timeout_seconds,
            "topology": self.topology,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SchedulingPolicy":
        return cls(
            min_available=d.get("minAvailable"),
            queue=d.get("queue", ""),
            min_resources=dict(d.get("minResources", {})),
            priority_class=d.get("priorityClass", ""),
            schedule_timeout_seconds=d.get("scheduleTimeoutSeconds"),
            topology=d.get("topology"),
        )


@dataclass
class RunPolicy:
    """Job-level execution policy (reference common_types.go:191-251)."""

    clean_pod_policy: Optional[CleanPodPolicy] = None
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None
    suspend: bool = False
    managed_by: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "cleanPodPolicy": self.clean_pod_policy.value if self.clean_pod_policy else None,
            "ttlSecondsAfterFinished": self.ttl_seconds_after_finished,
            "activeDeadlineSeconds": self.active_deadline_seconds,
            "backoffLimit": self.backoff_limit,
            "schedulingPolicy": self.scheduling_policy.to_dict() if self.scheduling_policy else None,
            "suspend": self.suspend,
            "managedBy": self.managed_by,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RunPolicy":
        sp = d.get("schedulingPolicy")
        cpp = d.get("cleanPodPolicy")
        return cls(
            clean_pod_policy=CleanPodPolicy(cpp) if cpp else None,
            ttl_seconds_after_finished=d.get("ttlSecondsAfterFinished"),
            active_deadline_seconds=d.get("activeDeadlineSeconds"),
            backoff_limit=d.get("backoffLimit"),
            scheduling_policy=SchedulingPolicy.from_dict(sp) if sp else None,
            suspend=bool(d.get("suspend", False)),
            managed_by=d.get("managedBy"),
        )


# ---------------------------------------------------------------------------
# Pod template & replica spec
# ---------------------------------------------------------------------------


@dataclass
class Container:
    """Minimal container spec for the virtual substrate and env injection."""

    name: str
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    ports: Dict[str, int] = field(default_factory=dict)
    resources: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Container":
        return cls(
            name=d["name"],
            image=d.get("image", ""),
            command=list(d.get("command", [])),
            args=list(d.get("args", [])),
            env=dict(d.get("env", {})),
            ports=dict(d.get("ports", {})),
            resources=dict(d.get("resources", {})),
        )


@dataclass
class PodTemplateSpec:
    """Pod template: containers + placement hints.

    `node_selector` / `affinity` are the surface the tpu-packer writes its
    placement decisions into (north-star: per-pod nodeSelector/affinity patches).
    """

    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    # Tolerations of node taints, k8s-shaped dicts:
    # {"key", "operator" ("Equal"|"Exists"), "value", "effect"}.
    tolerations: List[Dict[str, Any]] = field(default_factory=list)
    # Volumes, k8s-shaped dicts ({"name", ...source}); carried through to
    # pods verbatim (the substrate does not mount anything).
    volumes: List[Dict[str, Any]] = field(default_factory=list)
    scheduler_name: str = ""
    service_account: str = ""
    restart_policy: Optional[RestartPolicy] = None

    def main_container(self, name: str) -> Optional[Container]:
        for c in self.containers:
            if c.name == name:
                return c
        return None

    def resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for c in self.containers:
            for k, v in c.resources.items():
                total[k] = total.get(k, 0.0) + v
        return total

    def copy(self) -> "PodTemplateSpec":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "containers": [c.to_dict() for c in self.containers],
            "initContainers": [c.to_dict() for c in self.init_containers],
            "labels": dict(self.labels),
            "annotations": dict(self.annotations),
            "nodeSelector": dict(self.node_selector),
            "tolerations": [dict(t) for t in self.tolerations],
            "volumes": [dict(v) for v in self.volumes],
            "schedulerName": self.scheduler_name,
            "restartPolicy": self.restart_policy.value if self.restart_policy else None,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PodTemplateSpec":
        rp = d.get("restartPolicy")
        return cls(
            containers=[Container.from_dict(c) for c in d.get("containers", [])],
            init_containers=[Container.from_dict(c) for c in d.get("initContainers", [])],
            labels=dict(d.get("labels", {})),
            annotations=dict(d.get("annotations", {})),
            node_selector=dict(d.get("nodeSelector", {})),
            tolerations=[dict(t) for t in d.get("tolerations", [])],
            volumes=[dict(v) for v in d.get("volumes", [])],
            scheduler_name=d.get("schedulerName", ""),
            restart_policy=RestartPolicy(rp) if rp else None,
        )


@dataclass
class ReplicaSpec:
    """One replica group of a job (reference common_types.go ReplicaSpec)."""

    replicas: Optional[int] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    restart_policy: Optional[RestartPolicy] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "replicas": self.replicas,
            "template": self.template.to_dict(),
            "restartPolicy": self.restart_policy.value if self.restart_policy else None,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaSpec":
        rp = d.get("restartPolicy")
        return cls(
            replicas=d.get("replicas"),
            template=PodTemplateSpec.from_dict(d.get("template", {})),
            restart_policy=RestartPolicy(rp) if rp else None,
        )
