"""The v1-generation job kinds: JAXJob (primary), PyTorchJob, TFJob, XGBoostJob,
PaddleJob, MPIJob.

Parity target: reference pkg/apis/kubeflow.org/v1/{jax,pytorch,tensorflow,
xgboost,paddlepaddle,mpi}_types.go. Each kind is a thin declarative wrapper
around a map of replica-type -> ReplicaSpec plus a RunPolicy and kind-specific
policy knobs (ElasticPolicy, SuccessPolicy, SlotsPerWorker, ...).

TPU-first extension: every job may carry a `TPUPolicy` describing the slice/mesh
it wants (accelerator type, topology, mesh axes). The reference has no such
surface — its unit of parallelism is the replica (SURVEY.md §2.3); here mesh
axes are first-class so the placement engine can score ICI contiguity.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from training_operator_tpu.api.common import (
    JobStatus,
    ReplicaSpec,
    RunPolicy,
)

# Canonical replica-type names (reference <fw>_types.go constants).
REPLICA_MASTER = "Master"
REPLICA_WORKER = "Worker"
REPLICA_CHIEF = "Chief"
REPLICA_PS = "PS"
REPLICA_EVALUATOR = "Evaluator"
REPLICA_LAUNCHER = "Launcher"


@dataclass
class ObjectMeta:
    """Kubernetes-style object metadata for all API objects."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_time: Optional[float] = None
    deletion_time: Optional[float] = None
    resource_version: int = 0
    owner_uid: Optional[str] = None

    _uid_counter = itertools.count(1)

    def ensure_uid(self, kind: str) -> None:
        if not self.uid:
            self.uid = f"{kind.lower()}-{self.namespace}-{self.name}-{next(ObjectMeta._uid_counter)}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "namespace": self.namespace,
            "uid": self.uid,
            "labels": dict(self.labels),
            "annotations": dict(self.annotations),
            "creationTime": self.creation_time,
            "deletionTime": self.deletion_time,
            "resourceVersion": self.resource_version,
            "ownerUid": self.owner_uid,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid", ""),
            labels=dict(d.get("labels", {})),
            annotations=dict(d.get("annotations", {})),
            creation_time=d.get("creationTime"),
            deletion_time=d.get("deletionTime"),
            resource_version=d.get("resourceVersion", 0),
            owner_uid=d.get("ownerUid"),
        )


# ---------------------------------------------------------------------------
# TPU policy — the TPU-first extension (no reference analogue; SURVEY.md §2.3)
# ---------------------------------------------------------------------------


@dataclass
class TPUPolicy:
    """Declarative TPU slice / mesh request.

    accelerator: slice type, e.g. "v5e-8", "v5p-16".
    topology: requested physical ICI topology, e.g. "2x4" (chips per axis).
    num_slices: how many slices (multi-slice over DCN).
    mesh_axes: logical mesh axis names -> sizes, e.g. {"data": 2, "fsdp": 2,
        "tensor": 2}; product must equal total chips. Consumed by the trainer
        runtime to build a jax.sharding.Mesh and by tpu-packer to prefer
        contiguous ICI placements that realize these axes physically.
    """

    accelerator: str = "v5e-8"
    topology: Optional[str] = None
    num_slices: int = 1
    mesh_axes: Dict[str, int] = field(default_factory=dict)

    def chips_per_slice(self) -> int:
        if self.topology:
            dims = [int(x) for x in self.topology.lower().split("x")]
            prod = 1
            for x in dims:
                prod *= x
            return prod
        # "v5e-8" -> 8
        try:
            return int(self.accelerator.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            return 1

    def total_chips(self) -> int:
        return self.chips_per_slice() * self.num_slices

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUPolicy":
        return cls(
            accelerator=d.get("accelerator", "v5e-8"),
            topology=d.get("topology"),
            num_slices=d.get("num_slices", 1),
            mesh_axes=dict(d.get("mesh_axes", {})),
        )


# ---------------------------------------------------------------------------
# Kind-specific policies
# ---------------------------------------------------------------------------


class RDZVBackend(str, enum.Enum):
    C10D = "c10d"
    ETCD = "etcd"
    ETCD_V2 = "etcd-v2"


@dataclass
class RDZVConf:
    key: str = ""
    value: str = ""


@dataclass
class ElasticPolicy:
    """Elastic (torchrun) policy (reference pytorch_types.go:98-141)."""

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    rdzv_backend: Optional[RDZVBackend] = None
    rdzv_port: Optional[int] = None
    rdzv_host: Optional[str] = None
    rdzv_id: Optional[str] = None
    rdzv_conf: List[RDZVConf] = field(default_factory=list)
    standalone: Optional[bool] = None
    n_proc_per_node: Optional[int] = None
    max_restarts: Optional[int] = None
    # Metric specs driving the HPA-equivalent autoscaler: list of
    # {"name": ..., "target": float} utilization targets.
    metrics: List[Dict[str, Any]] = field(default_factory=list)


class SuccessPolicy(str, enum.Enum):
    """TFJob success policy (reference tensorflow_types.go:93-99)."""

    DEFAULT = ""
    ALL_WORKERS = "AllWorkers"


class MPIImplementation(str, enum.Enum):
    OPENMPI = "OpenMPI"
    INTEL = "Intel"
    MPICH = "MPICH"


# ---------------------------------------------------------------------------
# Job kinds
# ---------------------------------------------------------------------------


@dataclass
class Job:
    """Base declarative job: kind + metadata + replica specs + run policy.

    Concrete kinds add their policy knobs. `replica_specs` maps replica-type
    name (e.g. "Master", "Worker") to a ReplicaSpec, mirroring the reference's
    `<FW>ReplicaSpecs` maps.
    """

    KIND = "Job"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    replica_specs: Dict[str, ReplicaSpec] = field(default_factory=dict)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    tpu_policy: Optional[TPUPolicy] = None
    status: JobStatus = field(default_factory=JobStatus)

    @property
    def kind(self) -> str:
        return type(self).KIND

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def total_replicas(self) -> int:
        return sum(rs.replicas or 0 for rs in self.replica_specs.values())


@dataclass
class JAXJob(Job):
    """Distributed JAX job (reference jax_types.go:22-79).

    Worker-only: worker-0 is the coordinator (`jax.distributed.initialize`),
    reachable on `coordinator_port` (reference default 6666).
    This is the primary kind of the TPU-native framework.
    """

    KIND = "JAXJob"
    DEFAULT_PORT = 6666
    DEFAULT_PORT_NAME = "jaxjob-port"

    coordinator_port: int = DEFAULT_PORT


@dataclass
class PyTorchJob(Job):
    """PyTorch DDP/elastic job (reference pytorch_types.go:56-151)."""

    KIND = "PyTorchJob"
    DEFAULT_PORT = 23456
    DEFAULT_PORT_NAME = "pytorchjob-port"

    elastic_policy: Optional[ElasticPolicy] = None
    nproc_per_node: Optional[int] = None


@dataclass
class TFJob(Job):
    """TensorFlow job with PS/Worker/Chief/Master/Evaluator replicas
    (reference tensorflow_types.go:49-119)."""

    KIND = "TFJob"
    DEFAULT_PORT = 2222
    DEFAULT_PORT_NAME = "tfjob-port"

    success_policy: SuccessPolicy = SuccessPolicy.DEFAULT
    enable_dynamic_worker: bool = False


@dataclass
class XGBoostJob(Job):
    """XGBoost job with Rabit tracker bootstrap (reference xgboost_types.go)."""

    KIND = "XGBoostJob"
    DEFAULT_PORT = 9999
    DEFAULT_PORT_NAME = "xgboostjob-port"


@dataclass
class PaddleJob(Job):
    """PaddlePaddle collective job (reference paddlepaddle_types.go)."""

    KIND = "PaddleJob"
    DEFAULT_PORT = 37777
    DEFAULT_PORT_NAME = "paddlejob-port"


@dataclass
class MPIJob(Job):
    """MPI launcher/worker job (reference mpi_types.go).

    The TPU-native runtime drops the reference's `kubectl exec` rsh-agent hack
    (mpi/mpijob_controller.go:1227-1299) in favour of a hostfile + per-job
    ssh-less exec channel provided by the virtual substrate; slots_per_worker
    and the OpenMPI/Intel/MPICH env contracts are preserved.
    """

    KIND = "MPIJob"

    slots_per_worker: int = 1
    clean_pod_policy: Optional[str] = None
    main_container: str = ""
    mpi_implementation: MPIImplementation = MPIImplementation.OPENMPI
    run_launcher_as_node: bool = False


JOB_KINDS: Dict[str, type] = {
    k.KIND: k for k in (JAXJob, PyTorchJob, TFJob, XGBoostJob, PaddleJob, MPIJob)
}


def replica_types_for_kind(kind: str) -> List[str]:
    """Valid replica types per kind (reference <fw>_types.go constants)."""
    return {
        "JAXJob": [REPLICA_WORKER],
        "PyTorchJob": [REPLICA_MASTER, REPLICA_WORKER],
        "TFJob": [REPLICA_CHIEF, REPLICA_MASTER, REPLICA_PS, REPLICA_WORKER, REPLICA_EVALUATOR],
        "XGBoostJob": [REPLICA_MASTER, REPLICA_WORKER],
        "PaddleJob": [REPLICA_MASTER, REPLICA_WORKER],
        "MPIJob": [REPLICA_LAUNCHER, REPLICA_WORKER],
    }[kind]
