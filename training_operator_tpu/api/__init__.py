"""API layer: declarative job data model, defaulting, and validation.

Parity target: pkg/apis/kubeflow.org/v1 (common_types.go, <framework>_types.go) and
pkg/apis/kubeflow.org/v2alpha1 (trainjob_types.go, trainingruntime_types.go) in the
reference, re-designed as plain Python dataclasses with explicit defaulting and
validation passes (the reference performs these in admission webhooks).
"""

from training_operator_tpu.api.common import (
    CleanPodPolicy,
    JobCondition,
    JobConditionType,
    JobStatus,
    ReplicaSpec,
    ReplicaStatus,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
)
from training_operator_tpu.api.jobs import (
    ElasticPolicy,
    JAXJob,
    Job,
    MPIJob,
    PaddleJob,
    PyTorchJob,
    TFJob,
    XGBoostJob,
)

__all__ = [
    "CleanPodPolicy",
    "ElasticPolicy",
    "JAXJob",
    "Job",
    "JobCondition",
    "JobConditionType",
    "JobStatus",
    "MPIJob",
    "PaddleJob",
    "PyTorchJob",
    "ReplicaSpec",
    "ReplicaStatus",
    "RestartPolicy",
    "RunPolicy",
    "SchedulingPolicy",
    "TFJob",
    "XGBoostJob",
]
