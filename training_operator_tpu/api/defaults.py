"""Defaulting pass applied on admission and re-applied on reconcile.

Parity target: reference pkg/apis/kubeflow.org/v1/<fw>_defaults.go — default
replicas=1, default restart policy, default port injection, default
CleanPodPolicy/Suspend on RunPolicy — and `Scheme.Default` being re-applied at
the top of each reconcile (pytorchjob_controller.go:156).
"""

from __future__ import annotations

import time
from typing import Optional

from training_operator_tpu.api.common import (
    CleanPodPolicy,
    Container,
    RestartPolicy,
)
from training_operator_tpu.api.jobs import (
    JAXJob,
    Job,
    MPIJob,
    PaddleJob,
    PyTorchJob,
    TFJob,
    XGBoostJob,
)

# Default container name per kind (reference <fw>_types.go DefaultContainerName).
DEFAULT_CONTAINER_NAME = {
    "JAXJob": "jax",
    "PyTorchJob": "pytorch",
    "TFJob": "tensorflow",
    "XGBoostJob": "xgboost",
    "PaddleJob": "paddle",
    "MPIJob": "mpi",
    "TrainJob": "trainer",
}

DEFAULT_PORT = {
    "JAXJob": JAXJob.DEFAULT_PORT,
    "PyTorchJob": PyTorchJob.DEFAULT_PORT,
    "TFJob": TFJob.DEFAULT_PORT,
    "XGBoostJob": XGBoostJob.DEFAULT_PORT,
    "PaddleJob": PaddleJob.DEFAULT_PORT,
    "MPIJob": 0,  # MPI uses no Services (reference mpi controller)
}

DEFAULT_PORT_NAME = {
    "JAXJob": JAXJob.DEFAULT_PORT_NAME,
    "PyTorchJob": PyTorchJob.DEFAULT_PORT_NAME,
    "TFJob": TFJob.DEFAULT_PORT_NAME,
    "XGBoostJob": XGBoostJob.DEFAULT_PORT_NAME,
    "PaddleJob": PaddleJob.DEFAULT_PORT_NAME,
}

_DEFAULT_RESTART_POLICY = {
    # Reference: pytorch/tf/xgboost/paddle default OnFailure for workers;
    # MPI launcher defaults Never (reference mpi_defaults.go).
    "JAXJob": RestartPolicy.ON_FAILURE,
    "PyTorchJob": RestartPolicy.ON_FAILURE,
    "TFJob": RestartPolicy.ON_FAILURE,
    "XGBoostJob": RestartPolicy.ON_FAILURE,
    "PaddleJob": RestartPolicy.ON_FAILURE,
    "MPIJob": RestartPolicy.NEVER,
}


def default_job(job: Job, now: Optional[float] = None) -> Job:
    """Apply in-place defaulting; idempotent. Returns the job for chaining."""
    job.metadata.ensure_uid(job.kind)
    if job.metadata.creation_time is None:
        job.metadata.creation_time = time.time() if now is None else now

    if job.run_policy.clean_pod_policy is None:
        # Reference defaults CleanPodPolicy=None kind-dependently; v1 common
        # default is Running for MPI, None->All elsewhere in v2. We default to
        # Running to preserve failed pods for debugging, like mpi_defaults.go.
        job.run_policy.clean_pod_policy = (
            CleanPodPolicy.RUNNING if job.kind == "MPIJob" else CleanPodPolicy.NONE
        )

    for rtype, spec in job.replica_specs.items():
        if spec.replicas is None:
            spec.replicas = 1
        if spec.restart_policy is None:
            spec.restart_policy = _DEFAULT_RESTART_POLICY.get(
                job.kind, RestartPolicy.ON_FAILURE
            )
        _ensure_default_container(job, rtype)

    if isinstance(job, MPIJob) and not job.main_container:
        job.main_container = DEFAULT_CONTAINER_NAME["MPIJob"]
    if isinstance(job, PyTorchJob) and job.elastic_policy is not None:
        ep = job.elastic_policy
        if ep.max_restarts is None:
            ep.max_restarts = 10
        if ep.min_replicas is None:
            ep.min_replicas = job.replica_specs.get("Worker").replicas if job.replica_specs.get("Worker") else 1
        if ep.max_replicas is None:
            ep.max_replicas = ep.min_replicas
    return job


def _ensure_default_container(job: Job, rtype: str) -> None:
    spec = job.replica_specs[rtype]
    cname = DEFAULT_CONTAINER_NAME.get(job.kind, "trainer")
    if not spec.template.containers:
        spec.template.containers.append(Container(name=cname))
    port = DEFAULT_PORT.get(job.kind, 0)
    if isinstance(job, JAXJob):
        # The per-job coordinator_port knob IS the default port for JAXJobs;
        # injecting the static class default here would shadow it (the
        # controller's _port prefers the declared container port).
        port = job.coordinator_port
    pname = DEFAULT_PORT_NAME.get(job.kind)
    if port and pname:
        c = spec.template.main_container(cname)
        if c is not None and pname not in c.ports:
            c.ports[pname] = port
