"""MPIJob: hostfile + substrate exec channel (the reference's horovod path).

The TPU-native analogue of examples/mpi (tensorflow-mnist with horovodrun):
workers come up first, the controller generates the hostfile +
discover_hosts.sh ConfigMap, the launcher mounts it at /etc/mpi next to the
substrate exec-agent (replacing kubectl-delivery + per-job RBAC), and its
OpenMPI env points at both. The example prints the launcher's resolved file
view and drives the exec channel the way mpirun's rsh agent would.

Run: python examples/mpi_horovod.py
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from training_operator_tpu.utils.jaxenv import honor_cpu_platform_request

honor_cpu_platform_request()  # JAX_PLATFORMS=cpu wins over site-injected plugins

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import MPIJob, ObjectMeta
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.runtime import (
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
    resolve_pod_files,
)
from training_operator_tpu.controllers import OperatorManager, register_all


def main() -> None:
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_cpu_pool(4, cpu_per_node=16.0))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    mgr = OperatorManager(cluster)
    register_all(mgr)

    worker = PodTemplateSpec(
        containers=[Container(name="mpi", image="horovod/horovod:latest",
                              resources={"cpu": 4.0})]
    )
    launcher = PodTemplateSpec(
        containers=[
            Container(
                name="mpi",
                image="horovod/horovod:latest",
                command=["mpirun", "-np", "4", "python", "train.py"],
                resources={"cpu": 1.0},
            )
        ]
    )
    job = MPIJob(
        metadata=ObjectMeta(name="horovod"),
        replica_specs={
            "Launcher": ReplicaSpec(replicas=1, template=launcher),
            "Worker": ReplicaSpec(replicas=2, template=worker),
        },
        slots_per_worker=2,
    )
    mgr.submit(job)

    def launcher_pod():
        pods = cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "horovod"})
        return next((p for p in pods if "launcher" in p.name), None)

    assert cluster.run_until(lambda: launcher_pod() is not None, timeout=60)
    lp = launcher_pod()
    env = lp.spec.containers[0].env
    print("launcher env:")
    for k in sorted(k for k in env if k.startswith(("OMPI", "I_MPI", "HYDRA"))):
        print(f"   {k}={env[k]}")
    print("launcher mounted files:")
    for path, content in sorted(resolve_pod_files(cluster.api, lp).items()):
        first = content.splitlines()[0] if content else ""
        print(f"   {path}: {first!r} ...")
    # What mpirun's rsh agent does per hostfile entry:
    rc, _ = cluster.exec.exec_in_pod("default", "horovod-worker-0", ["orted", "--daemonize"])
    rc2, _ = cluster.exec.exec_in_pod("default", "horovod-worker-1", ["orted", "--daemonize"])
    print(f"exec channel into workers: rc={rc},{rc2}; log={cluster.exec.log}")


if __name__ == "__main__":
    main()
