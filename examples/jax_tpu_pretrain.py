"""Submit a multi-host JAX pretraining job on a v5e-16 slice.

The TPU-native analogue of the reference's examples/jax/ + examples/pytorch
distributed examples: a declarative JAXJob with a TPUPolicy; the operator
gang-schedules a contiguous 4x4 ICI sub-mesh via the tpu-packer and injects
the jax.distributed bootstrap + mesh geometry env.

Run: python examples/jax_tpu_pretrain.py
"""

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from training_operator_tpu.utils.jaxenv import honor_cpu_platform_request

honor_cpu_platform_request()  # JAX_PLATFORMS=cpu wins over site-injected plugins

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta, TPUPolicy
from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_tpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.scheduler import GangScheduler, TPUPacker
from training_operator_tpu.sdk import TrainingClient


def main():
    # A virtual 4-slice v5e pool (swap for a real cluster adapter in prod).
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(4, slice_topology="4x4"))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    GangScheduler(cluster, TPUPacker())
    mgr = OperatorManager(cluster, gang_enabled=True)
    register_all(mgr)
    client = TrainingClient(cluster)

    template = PodTemplateSpec(
        containers=[
            Container(
                name="jax",
                image="my-registry/llm-pretrain:latest",
                command=["python", "-m", "training_operator_tpu.examples_entry"],
                args=["--steps", "10000", "--seq-len", "8192"],
                resources={"cpu": 4.0, TPU_RESOURCE: 4.0},
            )
        ]
    )
    template.annotations[ANNOTATION_SIM_DURATION] = "30"  # sim only

    job = JAXJob(
        metadata=ObjectMeta(name="llm-pretrain"),
        replica_specs={"Worker": ReplicaSpec(replicas=4, template=template)},
        tpu_policy=TPUPolicy(
            accelerator="v5e-16",
            topology="4x4",
            mesh_axes={"data": 2, "fsdp": 4, "tensor": 2},
        ),
    )
    client.create_job(job)
    done = client.wait_for_job_conditions("llm-pretrain", timeout=300)
    print("conditions:", [c.type.value for c in done.status.conditions if c.status])
    for name in client.get_job_pod_names("llm-pretrain"):
        print("pod:", name)


if __name__ == "__main__":
    main()
