"""Elastic PyTorch DDP with a live utilization signal driving the HPA.

The TPU-native analogue of the reference's examples/pytorch/elastic (echo /
imagenet with torchrun --nnodes MIN:MAX): an ElasticPolicy on the job makes
the controller create an HPA; the pods publish a utilization profile that
RISES mid-run, the live ClusterMetricsSource picks it up, the HPA grows the
worker count, and the gang re-pack places only the delta pods — existing
members keep their nodes, exactly torchrun's membership contract.

Run: python examples/pytorch_elastic.py
"""

import json
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from training_operator_tpu.utils.jaxenv import honor_cpu_platform_request

honor_cpu_platform_request()  # JAX_PLATFORMS=cpu wins over site-injected plugins

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import ElasticPolicy, ObjectMeta, PyTorchJob
from training_operator_tpu.cluster.inventory import GPU_RESOURCE, make_gpu_pool
from training_operator_tpu.cluster.runtime import (
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.scheduler import GangScheduler, TPUPacker
from training_operator_tpu.scheduler.elastic import (
    ANNOTATION_LOAD_PROFILE_PREFIX,
    HorizontalAutoscaler,
)


def main() -> None:
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_gpu_pool(8, gpus_per_node=8, nodes_per_nvlink_domain=4))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    HorizontalAutoscaler(cluster, sync_period=5.0, stabilization_seconds=10.0)
    GangScheduler(cluster, TPUPacker())
    mgr = OperatorManager(cluster, gang_enabled=True)
    register_all(mgr)

    template = PodTemplateSpec(
        containers=[
            Container(
                name="pytorch",
                image="ghcr.io/example/ddp-trainer:latest",
                resources={"cpu": 4.0, GPU_RESOURCE: 8.0},
            )
        ]
    )
    # Pods report 70% GPU utilization for 30s, then 140% — the HPA formula
    # desired = ceil(current * actual/target) then doubles the fleet.
    template.annotations[ANNOTATION_LOAD_PROFILE_PREFIX + "gpu_util"] = json.dumps(
        [[0, 70.0], [30, 140.0]]
    )
    job = PyTorchJob(
        metadata=ObjectMeta(name="elastic-ddp"),
        replica_specs={"Worker": ReplicaSpec(replicas=2, template=template)},
        elastic_policy=ElasticPolicy(
            min_replicas=2,
            max_replicas=4,
            metrics=[{"name": "gpu_util", "target": 70.0}],
        ),
    )
    mgr.submit(job)

    def workers_running():
        return [
            p
            for p in cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "elastic-ddp"})
            if p.status.phase.value == "Running"
        ]

    assert cluster.run_until(lambda: len(workers_running()) == 2, timeout=60)
    print(f"t={cluster.clock.now():6.1f}s  2 workers running; load profile ramping...")
    assert cluster.run_until(lambda: len(workers_running()) == 4, timeout=300)
    pods = workers_running()
    print(f"t={cluster.clock.now():6.1f}s  scaled to {len(pods)} workers:")
    for p in sorted(pods, key=lambda p: p.name):
        print(f"   {p.name} -> {p.node_name} (PET_NNODES={p.spec.containers[0].env.get('PET_NNODES')})")


if __name__ == "__main__":
    main()
