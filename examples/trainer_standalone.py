"""The trainer-image entry: what runs INSIDE a scheduled JAXJob pod.

The TPU-native analogue of the reference's hf_llm_training.py (torchrun +
transformers.Trainer): consumes the bootstrap env the operator injected
(COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID / TPU_MESH_AXES), builds
the mesh, shards the data by process, runs the jitted train step, and
checkpoints — resumable after preemption or elastic re-mesh.

Run (single host, virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  TPU_MESH_AXES="fsdp=4,tensor=2" python examples/trainer_standalone.py
"""

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from training_operator_tpu.utils.jaxenv import honor_cpu_platform_request

honor_cpu_platform_request()  # JAX_PLATFORMS=cpu wins over site-injected plugins

import argparse
import os


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--checkpoint-dir", default="/tmp/tpu-trainer-ckpt")
    ap.add_argument("--resume", action="store_true")
    args = ap.parse_args()

    import jax

    # Multi-process bootstrap straight from the operator's env contract.
    num_processes = int(os.environ.get("NUM_PROCESSES", "1"))
    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=(
                f"{os.environ['COORDINATOR_ADDRESS']}:{os.environ['COORDINATOR_PORT']}"
            ),
            num_processes=num_processes,
            process_id=int(os.environ["PROCESS_ID"]),
        )

    from training_operator_tpu.trainer.checkpoint import Checkpointer, restore_into_mesh
    from training_operator_tpu.trainer.data import DataLoader, TokenDataset, prefetch, process_shard
    from training_operator_tpu.trainer.mesh import mesh_from_env
    from training_operator_tpu.trainer.model import TransformerConfig
    from training_operator_tpu.trainer.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
    )

    mesh = mesh_from_env()
    print("mesh:", dict(mesh.shape))

    config = TransformerConfig(
        vocab_size=4096, d_model=256, n_layers=4, n_heads=8, d_ff=688,
        max_seq_len=args.seq_len,
    )
    optimizer = make_optimizer(total_steps=args.steps)
    # Auto-resume whenever checkpoints exist: a preempted pod restarts with
    # the SAME command, so requiring a --resume flag would turn every
    # preemption into a crash loop. --resume stays for explicitness.
    have_ckpt = os.path.isdir(args.checkpoint_dir) and any(
        name.isdigit() for name in os.listdir(args.checkpoint_dir)
    )
    if args.resume or have_ckpt:
        state = restore_into_mesh(args.checkpoint_dir, config, optimizer, mesh)
        print("resumed at step", int(state.step))
    else:
        state = init_train_state(config, optimizer, jax.random.PRNGKey(0), mesh)

    pid, nproc = process_shard()
    dataset = TokenDataset.synthetic(
        config.vocab_size, args.seq_len, num_rows=args.batch_size * 8,
        process_id=pid, num_processes=nproc,
    )
    loader = DataLoader(dataset, args.batch_size, mesh)
    step_fn = make_train_step(config, optimizer, mesh)
    ckpt = Checkpointer(args.checkpoint_dir, save_interval_steps=10)

    done = int(state.step)
    epoch = 0
    while done < args.steps:
        for batch in prefetch(loader.epoch(epoch), size=2):
            state, metrics = step_fn(state, batch)
            done = int(metrics["step"])
            if done % 5 == 0 or done == args.steps:
                print(f"step {done} loss {float(metrics['loss']):.4f}")
            if done % 10 == 0:
                ckpt.save(state)
            if done >= args.steps:
                break
        epoch += 1
    ckpt.save(state, force=True)  # final save regardless of interval
    ckpt.close()
    # Model export (the v2 ModelConfig.Output path): when the operator
    # injected MODEL_EXPORT_URI, push the final checkpoint through the
    # scheme-dispatched initializer providers. Process 0 only.
    export_uri = os.environ.get("MODEL_EXPORT_URI")
    if export_uri and int(os.environ.get("PROCESS_ID", "0")) == 0:
        from training_operator_tpu.initializers import core as init_core

        # Export ONLY the final step's directory (retention keeps up to 3
        # checkpoints locally; consumers want one model, not a history).
        final_dir = os.path.join(args.checkpoint_dir, str(done))
        if not os.path.isdir(final_dir):
            raise SystemExit(
                f"export: final checkpoint dir {final_dir} not found — "
                "refusing to upload the whole retention history"
            )
        print("exporting to", init_core.upload(final_dir, export_uri))
    print("done at step", done)


if __name__ == "__main__":
    main()
