"""Train the vision/conv model family on synthetic MNIST-class data.

The reference's flagship example workload is an MNIST CNN in every framework
(examples/pytorch/mnist, examples/tensorflow/mnist, ...); here the same
family is a first-class trainer payload (trainer/vision.py) running directly
on the JAX backend — data-parallel over all local devices when more than one
is visible (e.g. XLA_FLAGS=--xla_force_host_platform_device_count=8).

Run: python examples/vision_mnist.py
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from training_operator_tpu.utils.jaxenv import honor_cpu_platform_request

honor_cpu_platform_request()  # JAX_PLATFORMS=cpu wins over site-injected plugins

import jax
import optax

from training_operator_tpu.trainer.mesh import MeshSpec, build_mesh
from training_operator_tpu.trainer.vision import (
    VisionConfig,
    init_vision_params,
    make_vision_train_step,
    synthetic_mnist,
    vision_param_shardings,
)


def main() -> None:
    config = VisionConfig()
    devices = jax.local_devices()
    mesh = None
    if len(devices) > 1:
        mesh = build_mesh(MeshSpec({"data": len(devices)}), devices)
        print(f"data-parallel over {len(devices)} devices")

    optimizer = optax.sgd(0.1, momentum=0.9)
    params = init_vision_params(config, jax.random.PRNGKey(0))
    if mesh is not None:
        params = jax.device_put(params, vision_param_shardings(config, mesh))
    opt_state = optimizer.init(params)
    step = make_vision_train_step(config, optimizer, mesh)

    batch = synthetic_mnist(jax.random.PRNGKey(1), 256, config)
    for i in range(60):
        params, opt_state, metrics = step(params, opt_state, batch)
        if i % 10 == 0 or i == 59:
            print(
                f"step {i:3d}  loss {float(metrics['loss']):.4f}  "
                f"accuracy {float(metrics['accuracy']):.3f}"
            )
    assert float(metrics["accuracy"]) > 0.9
    print("vision example: ok")


if __name__ == "__main__":
    main()
