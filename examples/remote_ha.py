"""The wire deployment, end to end: apiserver host + two operator replicas
as REAL OS processes, a job submitted over HTTPS (host-minted CA, verified), the elected leader
killed mid-run, and the standby converging the work.

This is the reference's production shape — operator pods with
--enable-leader-election against a kube-apiserver
(cmd/training-operator.v1/main.go:134-166) — on the TPU-native substrate:
`--role host` serves the cluster over HTTPS (scheduler + kubelet + admission
live there; TLS cert minted at startup, pkg/cert/cert.go:45 analogue),
`--role operator` runs only controllers + leader election against it, and
`TrainingClient("https://...", ca_file=...)` is the remote SDK.

Run: python examples/remote_ha.py
"""

import os as _os
import signal
import subprocess
import sys as _sys
import time

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from training_operator_tpu.utils.jaxenv import honor_cpu_platform_request

honor_cpu_platform_request()  # JAX_PLATFORMS=cpu wins over site-injected plugins

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import JAXJob, ObjectMeta
from training_operator_tpu.cluster.httpapi import RemoteAPIServer
from training_operator_tpu.cluster.runtime import ANNOTATION_SIM_DURATION
from training_operator_tpu.controllers.leader import DEFAULT_LEASE_NAME
from training_operator_tpu.sdk.client import TrainingClient

REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))


def _read_announcement(proc, prefix, timeout=30.0):
    from training_operator_tpu.utils.procio import read_announcement

    return read_announcement(proc, prefix, timeout=timeout)


def spawn(*args):
    return subprocess.Popen(
        [_sys.executable, "-m", "training_operator_tpu", *args],
        cwd=REPO, text=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env={**_os.environ, "PYTHONPATH": REPO, "PYTHONUNBUFFERED": "1"},
    )


def main():
    import json
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump({"cpu_pools": [{"nodes": 2, "cpu_per_node": 8.0}]}, f)
        inv = f.name

    host = spawn("--role", "host", "--serve-port", "0",
                 "--gang-scheduler-name", "none", "--cluster", inv)
    procs = [host]
    try:
        url = _read_announcement(host, "WIRE_API=", timeout=30.0)
        ca = _read_announcement(host, "WIRE_CA=", timeout=10.0)
        print(f"host up at {url} (CA: {ca})")

        ops = {}
        for ident in ("op-a", "op-b"):
            p = spawn("--role", "operator", "--api-server", url,
                      "--ca-cert", ca,
                      "--enable-scheme", "jax", "--gang-scheduler-name", "none",
                      "--enable-leader-election", "--leader-identity", ident,
                      "--leader-lease-seconds", "2")
            procs.append(p)
            ops[ident] = p
        print("two operator replicas racing one lease...")

        api = RemoteAPIServer(url, ca_file=ca)
        client = TrainingClient(url, ca_file=ca)
        lease = None
        for _ in range(300):
            lease = api.try_get("Lease", "operator-system", DEFAULT_LEASE_NAME)
            if lease is not None and lease.holder in ops:
                break
            time.sleep(0.1)
        assert lease is not None and lease.holder in ops, (
            f"no operator won the lease in time: {lease}"
        )
        leader = lease.holder
        standby = next(i for i in ops if i != leader)
        print(f"leader: {leader}  standby: {standby}")

        job = JAXJob(
            metadata=ObjectMeta(name="ha-demo"),
            replica_specs={"Worker": ReplicaSpec(
                replicas=2,
                template=PodTemplateSpec(
                    containers=[Container(name="jax", image="trainer",
                                          resources={"cpu": 1.0})],
                    annotations={ANNOTATION_SIM_DURATION: "5"},
                ),
            )},
        )
        client.create_job(job)
        client.wait_for_job_conditions(
            "ha-demo", expected_conditions=(capi.JobConditionType.RUNNING,),
            timeout=30,
        )
        print(f"job running under {leader}; kill -9 the leader")
        ops[leader].send_signal(signal.SIGKILL)
        ops[leader].wait()

        done = client.wait_for_job_conditions(
            "ha-demo", expected_conditions=(capi.JobConditionType.SUCCEEDED,),
            timeout=60,
        )
        lease = api.get("Lease", "operator-system", DEFAULT_LEASE_NAME)
        assert lease.holder == standby and capi.is_succeeded(done.status)
        print(f"standby {lease.holder} took the lease (transition "
              f"{lease.transitions}) and converged the job: Succeeded")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        _os.unlink(inv)


if __name__ == "__main__":
    main()
