"""v2 path: a reusable TrainingRuntime + a one-line TrainJob via the SDK.

Mirrors the reference's TrainJob/TrainingRuntime examples: the platform team
publishes a ClusterTrainingRuntime once (topology, mesh, gang policy, base
image); users submit TrainJobs that reference it, overriding only what they
own (dataset, model, args, node count).

Run: python examples/trainjob_v2.py
"""

import os as _os, sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from training_operator_tpu.utils.jaxenv import honor_cpu_platform_request

honor_cpu_platform_request()  # JAX_PLATFORMS=cpu wins over site-injected plugins

from training_operator_tpu.api.common import Container, PodTemplateSpec
from training_operator_tpu.api.jobs import ObjectMeta, TPUPolicy
from training_operator_tpu.cluster.inventory import TPU_RESOURCE, make_tpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all
from training_operator_tpu.runtime import ClusterTrainingRuntime, MLPolicy
from training_operator_tpu.runtime.api import (
    CoschedulingPolicy,
    PodGroupPolicy,
    ReplicatedJobTemplate,
    TrainingRuntimeSpec,
    TRAINER_NODE,
)
from training_operator_tpu.runtime.controller import TrainJobManager
from training_operator_tpu.scheduler import GangScheduler, TPUPacker
from training_operator_tpu.sdk import TrainingClient


def platform_runtime() -> ClusterTrainingRuntime:
    template = PodTemplateSpec(
        containers=[
            Container(
                name="trainer",
                image="my-registry/jax-trainer:stable",
                resources={"cpu": 4.0, TPU_RESOURCE: 4.0},
            )
        ]
    )
    template.annotations[ANNOTATION_SIM_DURATION] = "20"  # sim only
    return ClusterTrainingRuntime(
        metadata=ObjectMeta(name="v5e-16-pretrain", namespace=""),
        spec=TrainingRuntimeSpec(
            ml_policy=MLPolicy(
                num_nodes=4,
                tpu=TPUPolicy(accelerator="v5e-16", topology="4x4",
                              mesh_axes={"fsdp": 8, "tensor": 2}),
            ),
            pod_group_policy=PodGroupPolicy(coscheduling=CoschedulingPolicy(300)),
            template=[ReplicatedJobTemplate(name=TRAINER_NODE, replicas=4,
                                            template=template)],
        ),
    )


def main():
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_tpu_pool(4, slice_topology="4x4"))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    GangScheduler(cluster, TPUPacker())
    v1 = OperatorManager(cluster, gang_enabled=True)
    register_all(v1)
    TrainJobManager(cluster)
    client = TrainingClient(cluster)

    cluster.api.create(platform_runtime())

    client.train(
        name="squad-finetune",
        runtime_ref="v5e-16-pretrain",
        dataset_uri="hf://rajpurkar/squad",
        model_uri="hf://meta-llama/Llama-3.2-1B",
        output_uri="file:///checkpoints/squad-finetune",
        args=["--epochs", "3", "--lr", "2e-5"],
    )
    ok = cluster.run_until(
        lambda: cluster.api.get("TrainJob", "default", "squad-finetune").is_finished(),
        timeout=300,
    )
    tj = cluster.api.get("TrainJob", "default", "squad-finetune")
    print("finished:", ok, "| conditions:",
          [c.type.value for c in tj.status.conditions if c.status])


if __name__ == "__main__":
    main()
