"""TFJob: parameter-server training with generated TF_CONFIG.

The TPU-native analogue of the reference's examples/tensorflow (dist-mnist
with PS/worker/chief): the controller creates one headless Service per
replica and injects the TF_CONFIG JSON ({cluster: {...}, task: {type,
index}}) every replica needs; the chief's completion finishes the job
(default success policy).

Run: python examples/tensorflow_ps.py
"""

import json
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from training_operator_tpu.utils.jaxenv import honor_cpu_platform_request

honor_cpu_platform_request()  # JAX_PLATFORMS=cpu wins over site-injected plugins

import training_operator_tpu.api.common as capi
from training_operator_tpu.api.common import Container, PodTemplateSpec, ReplicaSpec
from training_operator_tpu.api.jobs import ObjectMeta, TFJob
from training_operator_tpu.cluster.inventory import make_cpu_pool
from training_operator_tpu.cluster.runtime import (
    ANNOTATION_SIM_DURATION,
    Cluster,
    DefaultScheduler,
    SimKubelet,
    VirtualClock,
)
from training_operator_tpu.controllers import OperatorManager, register_all


def tmpl(run_seconds=None):
    t = PodTemplateSpec(
        containers=[Container(name="tensorflow", image="tensorflow/tensorflow:latest",
                              resources={"cpu": 2.0})]
    )
    if run_seconds is not None:
        t.annotations[ANNOTATION_SIM_DURATION] = str(run_seconds)
    return t


def main() -> None:
    cluster = Cluster(VirtualClock())
    cluster.add_nodes(make_cpu_pool(4, cpu_per_node=16.0))
    DefaultScheduler(cluster)
    SimKubelet(cluster)
    mgr = OperatorManager(cluster)
    register_all(mgr)

    job = TFJob(
        metadata=ObjectMeta(name="dist-mnist"),
        replica_specs={
            "Chief": ReplicaSpec(replicas=1, template=tmpl(run_seconds=5)),
            "PS": ReplicaSpec(replicas=1, template=tmpl()),
            "Worker": ReplicaSpec(replicas=2, template=tmpl(run_seconds=5)),
        },
    )
    mgr.submit(job)
    assert cluster.run_until(
        lambda: capi.is_succeeded(cluster.api.get("TFJob", "default", "dist-mnist").status),
        timeout=120,
    )
    pods = cluster.api.list("Pod", "default", {capi.JOB_NAME_LABEL: "dist-mnist"})
    chief = next(p for p in pods if "chief" in p.name)
    tf_config = json.loads(chief.spec.containers[0].env["TF_CONFIG"])
    print("TF_CONFIG cluster roles:", sorted(tf_config["cluster"]))
    print("chief task:", tf_config["task"])
    print("services:", sorted(s.name for s in cluster.api.list("Service", "default")))
    print("job Succeeded on chief completion (PS still running is fine).")


if __name__ == "__main__":
    main()
